"""Data-model tests: time quantum, view, field, index, holder.

Modeled on reference field_test.go / index_test.go / time_test.go cases.
"""

import datetime as dt

import numpy as np
import pytest

from pilosa_tpu.core import timequantum as tq
from pilosa_tpu.core.field import (
    FIELD_TYPE_BOOL,
    FIELD_TYPE_INT,
    FIELD_TYPE_MUTEX,
    FIELD_TYPE_TIME,
    Field,
    FieldOptions,
    bit_depth_int,
)
from pilosa_tpu.core.holder import Holder
from pilosa_tpu.core.index import Index, IndexOptions
from pilosa_tpu.core.row import Row
from pilosa_tpu.errors import (
    BSIGroupValueTooHighError,
    BSIGroupValueTooLowError,
    FieldExistsError,
    NameError_,
)
from pilosa_tpu.pql import ast as pql_ast


# -- time quantum ----------------------------------------------------------

def test_views_by_time():
    t = dt.datetime(2017, 3, 2, 15)
    assert tq.views_by_time("standard", t, "YMDH") == [
        "standard_2017", "standard_201703", "standard_20170302",
        "standard_2017030215",
    ]
    assert tq.views_by_time("standard", t, "D") == ["standard_20170302"]


def test_views_by_time_range_ymdh():
    # Reference time_test.go TestViewsByTimeRange cases.
    out = tq.views_by_time_range(
        "std", dt.datetime(2016, 12, 30), dt.datetime(2017, 1, 3), "YMDH")
    assert out == ["std_20161230", "std_20161231", "std_20170101", "std_20170102"]

    out = tq.views_by_time_range(
        "std", dt.datetime(2016, 1, 1), dt.datetime(2018, 1, 1), "YMDH")
    assert out == ["std_2016", "std_2017"]

    out = tq.views_by_time_range(
        "std", dt.datetime(2016, 11, 30, 22), dt.datetime(2016, 12, 2, 2), "YMDH")
    assert out == ["std_2016113022", "std_2016113023", "std_20161201",
                   "std_2016120200", "std_2016120201"]


def test_views_by_time_range_no_hour_quantum():
    out = tq.views_by_time_range(
        "std", dt.datetime(2016, 5, 10), dt.datetime(2016, 5, 12), "YMD")
    assert out == ["std_20160510", "std_20160511"]


def test_parse_time():
    assert tq.parse_time("2017-01-02T03:04") == dt.datetime(2017, 1, 2, 3, 4)
    with pytest.raises(ValueError):
        tq.parse_time("bad")


# -- field: set ------------------------------------------------------------

def test_field_set_clear_bit():
    f = Field("i", "f")
    assert f.set_bit(1, 100)
    assert not f.set_bit(1, 100)
    assert sorted(f.row(1).columns().tolist()) == [100]
    assert f.clear_bit(1, 100)
    assert not f.clear_bit(1, 100)
    assert f.row(1).columns().tolist() == []


def test_field_name_validation():
    with pytest.raises(NameError_):
        Field("i", "UPPER")
    with pytest.raises(NameError_):
        Field("i", "9bad")
    with pytest.raises(NameError_):
        Field("i", "x" * 65)


def test_field_time_views():
    f = Field("i", "f", FieldOptions(type=FIELD_TYPE_TIME, time_quantum="YMD"))
    f.set_bit(1, 10, timestamp=dt.datetime(2017, 3, 2))
    assert set(f.view_names()) == {
        "standard", "standard_2017", "standard_201703", "standard_20170302"}
    got = f.row_time(1, dt.datetime(2017, 1, 1), dt.datetime(2018, 1, 1))
    assert got.columns().tolist() == [10]
    got = f.row_time(1, dt.datetime(2018, 1, 1), dt.datetime(2019, 1, 1))
    assert got.columns().tolist() == []


def test_field_mutex():
    f = Field("i", "f", FieldOptions(type=FIELD_TYPE_MUTEX))
    f.set_bit(1, 10)
    f.set_bit(2, 10)  # steals the column from row 1
    assert f.row(1).columns().tolist() == []
    assert f.row(2).columns().tolist() == [10]


def test_field_bool():
    f = Field("i", "f", FieldOptions(type=FIELD_TYPE_BOOL))
    f.set_bit(1, 5)   # true
    f.set_bit(0, 5)   # -> false
    assert f.row(1).columns().tolist() == []
    assert f.row(0).columns().tolist() == [5]


# -- field: int/BSI --------------------------------------------------------

def test_bsi_base_and_depth():
    f = Field("i", "f", FieldOptions(type=FIELD_TYPE_INT, min=10, max=1000))
    assert f.bsi_group.base == 10
    assert f.options.bit_depth == bit_depth_int(990)

    f = Field("i", "f", FieldOptions(type=FIELD_TYPE_INT, min=-100, max=-10))
    assert f.bsi_group.base == -10

    f = Field("i", "f", FieldOptions(type=FIELD_TYPE_INT, min=-5, max=5))
    assert f.bsi_group.base == 0


def test_set_value_get_value():
    f = Field("i", "f", FieldOptions(type=FIELD_TYPE_INT, min=-1000, max=1000))
    assert f.set_value(1, 42)
    assert f.set_value(2, -7)
    assert f.set_value(3, 0)
    assert f.value(1) == (42, True)
    assert f.value(2) == (-7, True)
    assert f.value(3) == (0, True)
    assert f.value(99) == (0, False)
    # overwrite
    f.set_value(1, -42)
    assert f.value(1) == (-42, True)


def test_set_value_range_validation():
    f = Field("i", "f", FieldOptions(type=FIELD_TYPE_INT, min=0, max=100))
    with pytest.raises(BSIGroupValueTooLowError):
        f.set_value(1, -1)
    with pytest.raises(BSIGroupValueTooHighError):
        f.set_value(1, 101)


def test_sum_min_max():
    f = Field("i", "f", FieldOptions(type=FIELD_TYPE_INT, min=-1000, max=1000))
    vals = {1: 10, 2: -20, 3: 30, 5: 0}
    for c, v in vals.items():
        f.set_value(c, v)
    s, c = f.sum()
    assert (s, c) == (20, 4)
    assert f.min() == (-20, 1)
    assert f.max() == (30, 1)
    filt = Row.from_columns([1, 2])
    s, c = f.sum(filt)
    assert (s, c) == (-10, 2)


def test_field_range_queries():
    f = Field("i", "f", FieldOptions(type=FIELD_TYPE_INT, min=-100, max=100))
    for c, v in {1: 10, 2: -20, 3: 30, 4: 0}.items():
        f.set_value(c, v)
    assert f.range(pql_ast.GT, 5).columns().tolist() == [1, 3]
    assert f.range(pql_ast.LT, 0).columns().tolist() == [2]
    assert f.range(pql_ast.EQ, 30).columns().tolist() == [3]
    assert f.range(pql_ast.NEQ, 30).columns().tolist() == [1, 2, 4]
    assert f.range(pql_ast.LTE, 0).columns().tolist() == [2, 4]
    assert f.range_between(-20, 10).columns().tolist() == [1, 2, 4]
    assert f.not_null().columns().tolist() == [1, 2, 3, 4]


def test_import_values_and_bits():
    f = Field("i", "f", FieldOptions(type=FIELD_TYPE_INT, min=0, max=10**6))
    cols = np.arange(0, 5000, 7, dtype=np.uint64)
    vals = (cols * 3).astype(np.int64)
    f.import_values(cols.tolist(), vals.tolist())
    s, c = f.sum()
    assert c == len(cols)
    assert s == int(vals.sum())

    g = Field("i", "g")
    g.import_bits([1, 1, 2], [5, 9, 5])
    assert g.row(1).columns().tolist() == [5, 9]
    assert g.row(2).columns().tolist() == [5]


# -- index / holder --------------------------------------------------------

def test_index_create_field_and_existence():
    idx = Index("i")
    f = idx.create_field("f")
    assert idx.field("f") is f
    assert idx.existence_field() is not None
    with pytest.raises(FieldExistsError):
        idx.create_field("f")
    idx.add_existence([1, 5])
    assert idx.existence_row().columns().tolist() == [1, 5]
    # _exists is hidden from public listing
    assert [x.name for x in idx.public_fields()] == ["f"]


def test_index_no_existence_tracking():
    idx = Index("i", IndexOptions(track_existence=False))
    assert idx.existence_field() is None


def test_holder_schema_roundtrip():
    h = Holder()
    idx = h.create_index("myindex", IndexOptions(keys=False))
    idx.create_field("fset")
    idx.create_field("fint", FieldOptions(type=FIELD_TYPE_INT, min=0, max=100))
    idx.create_field("ftime", FieldOptions(type=FIELD_TYPE_TIME, time_quantum="YMD"))

    schema = h.schema()
    h2 = Holder()
    h2.apply_schema(schema)
    assert h2.schema() == schema
    assert h2.field("myindex", "fint").options.type == FIELD_TYPE_INT


def test_holder_fragment_accessor():
    h = Holder()
    idx = h.create_index("i")
    f = idx.create_field("f")
    f.set_bit(3, 42)
    frag = h.fragment("i", "f", "standard", 0)
    assert frag is not None
    assert frag.contains(3, 42)
    assert h.fragment("i", "f", "standard", 9) is None
    assert h.fragment("i", "nope", "standard", 0) is None


def test_available_shards():
    from pilosa_tpu.config import SHARD_WIDTH
    idx = Index("i")
    f = idx.create_field("f")
    f.set_bit(0, 1)
    f.set_bit(0, SHARD_WIDTH * 3 + 5)
    assert f.available_shards() == {0, 3}
    assert idx.available_shards() == {0, 3}
    f.add_remote_available_shards([7])
    assert f.available_shards() == {0, 3, 7}
