"""MeshPlanner tests: SPMD execution over the 8-virtual-device CPU mesh
must agree exactly with the per-shard scalar executor path.

This is the analog of the reference's 1-node vs 3-node cluster equivalence
tests (executor_test.go: test.MustRunCluster(t, 3) mirrors of single-node
cases).
"""

import numpy as np
import pytest

import jax

from pilosa_tpu.config import SHARD_WIDTH
from pilosa_tpu.core import Holder, FieldOptions, IndexOptions
from pilosa_tpu.core.field import FIELD_TYPE_INT, FIELD_TYPE_TIME
from pilosa_tpu.exec import Executor
from pilosa_tpu.parallel import MeshPlanner, make_mesh


@pytest.fixture(scope="module")
def mesh():
    assert len(jax.devices()) == 8, "conftest must provide 8 virtual devices"
    return make_mesh()


@pytest.fixture
def env(mesh):
    h = Holder()
    idx = h.create_index("i")
    plain = Executor(h)
    fast = Executor(h, planner=MeshPlanner(h, mesh))
    return h, idx, plain, fast


def seed(idx, rng, n_shards=5, n_rows=6, bits_per_row=3000):
    f = idx.create_field("f")
    g = idx.create_field("g")
    v = idx.create_field("v", FieldOptions(type=FIELD_TYPE_INT, min=-500, max=500))
    total = n_shards * SHARD_WIDTH
    for field in (f, g):
        rows = rng.integers(0, n_rows, n_rows * bits_per_row)
        cols = rng.integers(0, total, n_rows * bits_per_row)
        field.import_bits(rows, cols)
    vcols = rng.choice(total, 5000, replace=False)
    vvals = rng.integers(-500, 500, len(vcols))
    v.import_values(vcols.tolist(), vvals.tolist())
    idx.add_existence(np.arange(0, total, 7))
    return f, g, v


QUERIES = [
    "Count(Row(f=1))",
    "Count(Intersect(Row(f=1), Row(g=2)))",
    "Count(Union(Row(f=0), Row(g=0), Row(f=3)))",
    "Count(Difference(Row(f=1), Row(g=1)))",
    "Count(Xor(Row(f=2), Row(g=2)))",
    "Count(Not(Row(f=1)))",
    "Count(Shift(Row(f=1), n=3))",
    "Count(Intersect(Union(Row(f=0), Row(f=1)), Not(Row(g=5))))",
    "Count(Row(v > 100))",
    "Count(Row(v < -100))",
    "Count(Row(v == 42))",
    "Count(Row(v != 42))",
    "Count(Row(v != null))",
    "Count(Row(v >< [-50, 50]))",
    "Count(Intersect(Row(f=1), Row(v >= 0)))",
]


@pytest.mark.parametrize("query", QUERIES)
def test_planner_matches_scalar_path(env, query):
    h, idx, plain, fast = env
    seed(idx, np.random.default_rng(11))
    expected = plain.execute("i", query)
    got = fast.execute("i", query)
    assert got == expected, (query, got, expected)


def test_planner_bitmap_result_matches(env):
    h, idx, plain, fast = env
    seed(idx, np.random.default_rng(12))
    for query in ["Row(f=1)", "Intersect(Row(f=1), Row(g=2))",
                  "Union(Row(f=0), Row(g=3))", "Row(v > 0)"]:
        (a,) = plain.execute("i", query)
        (b,) = fast.execute("i", query)
        assert np.array_equal(a.columns(), b.columns()), query


def test_planner_cache_invalidation_on_write(env):
    h, idx, plain, fast = env
    f = idx.create_field("f")
    f.import_bits([1, 1], [0, SHARD_WIDTH + 1])
    assert fast.execute("i", "Count(Row(f=1))") == [2]
    # Mutate and re-query: stale stacks must be refreshed.
    f.set_bit(1, 2 * SHARD_WIDTH + 2)
    assert fast.execute("i", "Count(Row(f=1))") == [3]
    f.clear_bit(1, 0)
    assert fast.execute("i", "Count(Row(f=1))") == [2]


def test_planner_time_range(env):
    h, idx, plain, fast = env
    import datetime as dt
    t = idx.create_field("t", FieldOptions(type=FIELD_TYPE_TIME, time_quantum="YMD"))
    t.set_bit(1, 5, timestamp=dt.datetime(2018, 3, 1))
    t.set_bit(1, SHARD_WIDTH + 9, timestamp=dt.datetime(2018, 6, 1))
    t.set_bit(1, 7, timestamp=dt.datetime(2019, 1, 1))
    q = "Count(Row(t=1, from='2018-01-01T00:00', to='2019-01-01T00:00'))"
    assert fast.execute("i", q) == plain.execute("i", q) == [2]


def test_planner_sharding_layout(env):
    """The stacked leaf really is partitioned across the mesh devices."""
    h, idx, plain, fast = env
    f = idx.create_field("f")
    cols = [s * SHARD_WIDTH for s in range(16)]
    f.import_bits([1] * 16, cols)
    planner = fast.planner
    from pilosa_tpu.pql import parse
    call = parse("Row(f=1)").calls[0]
    shards = sorted(idx.available_shards())
    assert fast.execute("i", "Count(Row(f=1))") == [16]
    stack = planner._stack_rows(idx, "f", "standard", 1, tuple(shards))
    assert stack.shape[0] == 16
    # 16 shards over 8 devices -> 2 shard-rows per device
    assert len(stack.sharding.device_set) == 8


def test_shift_default_matches_scalar(env):
    h, idx, plain, fast = env
    f = idx.create_field("f")
    f.import_bits([1, 1, 1], [0, 5, 9])
    q = "Count(Shift(Row(f=1)))"
    assert fast.execute("i", q) == plain.execute("i", q)
    (a,) = plain.execute("i", "Shift(Row(f=1))")
    (b,) = fast.execute("i", "Shift(Row(f=1))")
    assert np.array_equal(a.columns(), b.columns())


def test_bsi_predicates_share_compiled_program(env):
    h, idx, plain, fast = env
    seed(idx, np.random.default_rng(13))
    planner = fast.planner
    for v in range(5):
        fast.execute("i", f"Count(Row(v > {v}))")
    # One compiled program for all five literals (magnitudes are traced).
    assert len(planner._fn_cache) == 1
    for v in range(3):
        got = fast.execute("i", f"Count(Row(v > {v}))")
        assert got == plain.execute("i", f"Count(Row(v > {v}))")


def test_cluster_nodes_use_planner():
    from pilosa_tpu.cluster.harness import LocalCluster
    from pilosa_tpu.parallel import MeshPlanner
    lc = LocalCluster(3, planner_factory=lambda i: None)
    # attach planners bound to each node's holder after construction
    for cn in lc.nodes:
        cn.executor.planner = MeshPlanner(cn.holder)
    lc.create_index("i")
    lc.create_field("i", "f")
    cols = [3, SHARD_WIDTH + 5, 2 * SHARD_WIDTH + 7]
    for c in cols:
        lc.query("i", f"Set({c}, f=9)")
    assert lc.query("i", "Count(Row(f=9))") == [3]
    # planner actually engaged on at least one node
    assert any(cn.executor.planner._fn_cache for cn in lc.nodes)


# ------------------------------------------- aggregates on the mesh (round 2)

AGG_QUERIES = [
    "Sum(field=v)",
    "Sum(Row(f=1), field=v)",
    "Sum(Intersect(Row(f=1), Row(g=2)), field=v)",
    "Min(field=v)",
    "Min(Row(f=2), field=v)",
    "Max(field=v)",
    "Max(Row(f=2), field=v)",
    "Min(Row(v < 0), field=v)",
    "Max(Row(v >= -100), field=v)",
]


@pytest.mark.parametrize("q", AGG_QUERIES)
def test_planner_aggregates_match_scalar(env, rng, q):
    """Sum/Min/Max through one SPMD program == per-shard scalar path
    (VERDICT r1 #4: planner must cover aggregates)."""
    h, idx, plain, fast = env
    seed(idx, rng)
    (want,) = plain.execute("i", q)
    (got,) = fast.execute("i", q)
    assert (got.val, got.count) == (want.val, want.count), q


def test_planner_agg_supports(env, rng):
    h, idx, plain, fast = env
    seed(idx, rng)
    from pilosa_tpu.pql import parse
    p = fast.planner
    assert p.supports_aggregate(idx, parse("Sum(field=v)").calls[0])
    assert p.supports_aggregate(idx, parse("Min(Row(f=1), field=v)").calls[0])
    assert not p.supports_aggregate(idx, parse("Sum(field=f)").calls[0])
    assert not p.supports_aggregate(idx, parse("Count(Row(f=1))").calls[0])
    # Unknown filter field: supported structurally, raises at execution —
    # matching the scalar path.
    from pilosa_tpu.errors import FieldNotFoundError
    with pytest.raises(FieldNotFoundError):
        fast.execute("i", "Sum(Row(nosuch=1), field=v)")
    with pytest.raises(FieldNotFoundError):
        plain.execute("i", "Sum(Row(nosuch=1), field=v)")


def test_planner_agg_empty_field(env, rng):
    """Aggregate over a BSI field with no values set."""
    h, idx, plain, fast = env
    idx.create_field("w", FieldOptions(type=FIELD_TYPE_INT, min=0, max=10))
    idx.create_field("f")
    for q in ("Sum(field=w)", "Min(field=w)", "Max(field=w)"):
        (want,) = plain.execute("i", q)
        (got,) = fast.execute("i", q)
        assert (got.val, got.count) == (want.val, want.count) == (0, 0), q


TOPN_QUERIES = [
    "TopN(f, n=4)",
    "TopN(f)",
    "TopN(f, Row(g=1), n=3)",
    "TopN(f, Intersect(Row(g=1), Row(g=2)), n=5)",
    "TopN(f, Row(g=0), n=2, threshold=10)",
    "TopN(f, ids=[0, 2, 4])",
    "TopN(f, Row(g=3), ids=[1, 3])",
]


@pytest.mark.parametrize("q", TOPN_QUERIES)
def test_planner_topn_matches_scalar(env, rng, q):
    """TopN through the sparse-aware streamed planner path == per-shard
    scalar path (VERDICT r1 #4: TopN pass-1 counts on the mesh)."""
    h, idx, plain, fast = env
    seed(idx, rng)
    (want,) = plain.execute("i", q)
    (got,) = fast.execute("i", q)
    assert [(p.id, p.count) for p in got] == \
        [(p.id, p.count) for p in want], q


def test_planner_topn_streams_tiles(env, rng, monkeypatch):
    """The planner TopN path must bound device stacks by TOPN_TILE."""
    from pilosa_tpu.parallel import planner as planmod
    h, idx, plain, fast = env
    seed(idx, rng, n_rows=40)
    from pilosa_tpu.ops import pallas_kernels
    from pilosa_tpu.core import fragment as fragmod
    monkeypatch.setattr(fragmod, "STACK_CACHE_MAX_ROWS", 8)
    monkeypatch.setattr(fragmod, "ROW_TILE", 8)
    seen = {"max": 0}
    real = pallas_kernels.pair_count

    def spy(a, b, op="and"):
        if hasattr(a, "ndim") and a.ndim == 2:
            seen["max"] = max(seen["max"], int(a.shape[0]))
        return real(a, b, op)

    monkeypatch.setattr(pallas_kernels, "pair_count", spy)
    (got,) = fast.execute("i", "TopN(f, Row(g=1), n=5)")
    (want,) = plain.execute("i", "TopN(f, Row(g=1), n=5)")
    # Dense rows stream in bounded tiles; sparse rows never touch the
    # device at all (host membership path).
    assert seen["max"] <= 8
    assert [(p.id, p.count) for p in got] == [(p.id, p.count) for p in want]


def test_prepared_count_fast_path_invalidation(mesh):
    """execute_async's prepared-query cache must never serve stale
    programs: a write (data epoch), a schema change, and a different
    shards list each force a correct re-plan."""
    h = Holder()
    idx = h.create_index("prep")
    f = idx.create_field("f")
    g = idx.create_field("g")
    cols = [0, 1, SHARD_WIDTH, SHARD_WIDTH + 1, 2 * SHARD_WIDTH]
    for c in cols:
        f.import_bits([1], [c])
        g.import_bits([2], [c])
    ex = Executor(h, planner=MeshPlanner(h, mesh))
    q = "Count(Intersect(Row(f=1), Row(g=2)))"

    assert ex.execute_async("prep", q, cache=False).result() == [5]
    # Second call rides the prepared entry.
    assert ("prep", q) in ex._prepared
    assert ex.execute_async("prep", q, cache=False).result() == [5]

    # Data write: epoch bump -> re-plan, new bit visible.
    ex.execute("prep", f"Set({3 * SHARD_WIDTH}, f=1)")
    ex.execute("prep", f"Set({3 * SHARD_WIDTH}, g=2)")
    assert ex.execute_async("prep", q, cache=False).result() == [6]

    # Explicit shards subset: prepared full-range entry must not serve.
    assert ex.execute_async("prep", q, shards=[0],
                            cache=False).result() == [2]

    # Schema change: delete/recreate the index -> instance_id differs.
    h.delete_index("prep")
    idx = h.create_index("prep")
    idx.create_field("f")
    idx.create_field("g")
    assert ex.execute_async("prep", q, cache=False).result() == [0]


def test_prepared_entry_dropped_when_stale(mesh):
    """Stale prepared entries release their device-array references
    immediately (HBM pinning guard)."""
    h = Holder()
    idx = h.create_index("prep2")
    idx.create_field("f")
    ex = Executor(h, planner=MeshPlanner(h, mesh))
    ex.execute("prep2", "Set(1, f=1)")
    q = "Count(Row(f=1))"
    assert ex.execute_async("prep2", q, cache=False).result() == [1]
    assert ("prep2", q) in ex._prepared
    ex.execute("prep2", "Set(2, f=1)")  # bump epoch
    # Next async call sees the stale entry, drops it, re-plans.
    assert ex.execute_async("prep2", q, cache=False).result() == [2]
    e = ex._prepared.get(("prep2", q))
    assert e is not None and e[2] == idx.epoch.value


def test_prepared_subset_never_serves_full_query(mesh):
    """A prepared entry built for an explicit shards subset must NOT
    answer a later shards=None (full index) query."""
    h = Holder()
    idx = h.create_index("prep3")
    idx.create_field("f")
    ex = Executor(h, planner=MeshPlanner(h, mesh))
    for c in (0, SHARD_WIDTH, 2 * SHARD_WIDTH):
        ex.execute("prep3", f"Set({c}, f=1)")
    q = "Count(Row(f=1))"
    # Prime the prepared cache with a SUBSET program.
    assert ex.execute_async("prep3", q, shards=[0],
                            cache=False).result() == [1]
    # Full query must re-plan, not ride the subset entry.
    assert ex.execute_async("prep3", q, cache=False).result() == [3]
    # And a full-prepared entry keeps serving full queries.
    assert ex.execute_async("prep3", q, cache=False).result() == [3]


def test_shift_full_range_device_vs_oracle(mesh):
    """VERDICT r4 #8: Shift supports ANY 0 <= n <= SHARD_WIDTH on
    device. Property-check the planner path against a positions oracle
    (per-shard semantics: bits shifted past a shard edge fall off)."""
    import numpy as np

    h = Holder()
    idx = h.create_index("sh")
    idx.create_field("f")
    rng = np.random.default_rng(99)
    n_shards = 3
    cols = rng.choice(n_shards * SHARD_WIDTH, 5000, replace=False)
    f = idx.field("f")
    f.import_bits(np.ones(len(cols), dtype=np.uint64),
                  cols.astype(np.uint64))
    ex = Executor(h, planner=MeshPlanner(h, mesh))
    planner = ex.planner

    local = cols % SHARD_WIDTH
    shard_of = cols // SHARD_WIDTH
    ns = [0, 1, 31, 32, 33, 63, 64, 65, 1000, SHARD_WIDTH - 1, SHARD_WIDTH,
          *rng.integers(0, SHARD_WIDTH, 6).tolist()]
    for n in ns:
        q = f"Count(Shift(Row(f=1), n={n}))"
        call = ex._parse_cached(q).calls[0]
        assert planner.supports(call.children[0]), n
        (got,) = ex.execute("sh", q, cache=False)
        expected = int(np.sum(local + n < SHARD_WIDTH))
        assert got == expected, (n, got, expected)
        # Host per-shard path agrees.
        host = Executor(h)  # no planner
        (hgot,) = host.execute("sh", q, cache=False)
        assert hgot == expected, (n, hgot, expected)


# -- stack-cache eviction under an over-subscribed HBM budget (VERDICT
# r4 missing #2 / weak #4): fill past max_cache_bytes and prove LRU
# order, byte accounting, correctness after evict, and that in-flight
# strong refs never go stale.


def _stack_key_rows(planner):
    """row ids currently resident, in LRU order (oldest first)."""
    return [k[4] for k in planner._stack_cache]


def test_stack_cache_evicts_lru_and_accounts_bytes(mesh, rng, monkeypatch):
    # These rows are sparse enough to pack under residency auto mode;
    # the exact byte arithmetic below is the dense class's contract.
    monkeypatch.setenv("PILOSA_TPU_RESIDENCY_PACKED", "off")
    h = Holder()
    idx = h.create_index("ev")
    f = idx.create_field("f")
    n_shards = 8
    total = n_shards * SHARD_WIDTH
    for r in range(6):
        cols = rng.integers(0, total, 2000)
        f.import_bits(np.full(len(cols), r), cols)
    # One leaf stack = S_pad(8) * W * 4 bytes; budget fits exactly 3.
    stack_bytes = 8 * (SHARD_WIDTH // 32) * 4
    planner = MeshPlanner(h, mesh, max_cache_bytes=3 * stack_bytes)
    e = Executor(h, planner=planner, result_cache=False)
    shards = list(range(n_shards))

    counts = {}
    for r in range(6):  # 6 distinct leaves through a 3-stack budget
        (counts[r],) = e.execute("ev", f"Count(Row(f={r}))", shards=shards)
    st = planner.cache_stats()
    assert st["entries"] == 3
    assert st["bytes"] == 3 * stack_bytes          # exact accounting
    assert st["bytes"] <= st["budget_bytes"]
    assert st["evictions"] == 3                    # 6 leaves, 3 survived
    assert _stack_key_rows(planner) == [3, 4, 5]   # LRU order: oldest out

    # Touch the LRU entry; it must move to MRU and survive the next
    # insert, which evicts row 4 instead.
    (again,) = e.execute("ev", "Count(Row(f=3))", shards=shards)
    assert again == counts[3]
    (c0,) = e.execute("ev", "Count(Row(f=0))", shards=shards)  # re-upload
    assert c0 == counts[0]                          # correct after evict
    assert _stack_key_rows(planner) == [5, 3, 0]
    assert planner.cache_stats()["bytes"] == 3 * stack_bytes
    assert planner.cache_stats()["evictions"] == 4

    # Full sweep again: every answer identical under eviction churn.
    for r in range(6):
        (c,) = e.execute("ev", f"Count(Row(f={r}))", shards=shards)
        assert c == counts[r]


def test_stack_cache_eviction_does_not_break_inflight_refs(mesh, rng,
                                                           monkeypatch):
    """An evicted entry's device array may still be referenced by an
    in-flight prepared plan; eviction only drops the cache's ref, so
    the dispatch must keep returning correct results (planner.py notes
    strong refs pin entries mid-query)."""
    monkeypatch.setenv("PILOSA_TPU_RESIDENCY_PACKED", "off")  # dense contract
    h = Holder()
    idx = h.create_index("ev2")
    f = idx.create_field("f")
    n_shards = 8
    total = n_shards * SHARD_WIDTH
    for r in range(4):
        cols = rng.integers(0, total, 2000)
        f.import_bits(np.full(len(cols), r), cols)
    stack_bytes = 8 * (SHARD_WIDTH // 32) * 4
    planner = MeshPlanner(h, mesh, max_cache_bytes=2 * stack_bytes)
    e = Executor(h, planner=planner, result_cache=False)
    shards = list(range(n_shards))

    from pilosa_tpu.pql import parse
    call = parse("Count(Row(f=0))").calls[0].children[0]
    fn, arrays = planner.prepare_count(idx, call, shards)
    want = planner._sum_host(np.asarray(fn(*arrays)))

    # Evict row 0's stack by churning three other leaves through the
    # 2-stack budget.
    for r in range(1, 4):
        e.execute("ev2", f"Count(Row(f={r}))", shards=shards)
    assert 0 not in _stack_key_rows(planner)

    # The held arrays still dispatch correctly post-evict...
    got = planner._sum_host(np.asarray(fn(*arrays)))
    assert got == want
    # ...and a fresh prepare re-resolves leaves through the cache.
    fn2, arrays2 = planner.prepare_count(idx, call, shards)
    assert planner._sum_host(np.asarray(fn2(*arrays2))) == want


def test_pallas_count_program_wiring(rng):
    """The opt-in fused count path's slot/op wiring, exercised on CPU
    (gate forced on; pallas falls back to interpret mode off-TPU, tiny
    shapes keep it fast). Guards the args[i]/args[j] leaf-slot indexing
    and the op table against silent regressions that would otherwise
    only surface on an operator's TPU rig with PILOSA_TPU_PALLAS_COUNT
    set."""
    h = Holder()
    idx = h.create_index("pc")
    f = idx.create_field("f")
    g = idx.create_field("g")
    total = SHARD_WIDTH
    f.import_bits(rng.integers(0, 3, 3000), rng.integers(0, total, 3000))
    g.import_bits(rng.integers(0, 3, 3000), rng.integers(0, total, 3000))
    planner = MeshPlanner(h, make_mesh(n=1))
    planner._pallas_count_enabled = lambda: True
    fast = Executor(h, planner=planner, result_cache=False)
    scalar = Executor(h)
    queries = ["Count(Row(f=1))",
               "Count(Intersect(Row(f=1), Row(g=2)))",
               "Count(Union(Row(f=0), Row(g=0)))",
               "Count(Xor(Row(f=1), Row(g=1)))",
               "Count(Difference(Row(f=2), Row(g=2)))"]
    for q in queries:
        (got,) = fast.execute("pc", q, cache=False)
        (want,) = scalar.execute("pc", q, cache=False)
        assert got == want, (q, got, want)
    # The fused program really was selected for these shapes.
    assert planner._pallas_count_program(("leaf", 0)) is not None
    assert planner._pallas_count_program(
        ("intersect", (("leaf", 0), ("leaf", 1)))) is not None
    # Deeper trees fall back to the generic XLA program.
    assert planner._pallas_count_program(
        ("not", 0, ("leaf", 1))) is None


def test_sparse_upload_stack_matches_dense(rng):
    """The sparse COO upload path must build bit-identical stacks to
    the dense device_put path across sparse, dense, mid-size, and
    empty rows (gate forced on; on CPU it is correctness-only)."""
    h = Holder()
    idx = h.create_index("su")
    f = idx.create_field("f")
    n_shards = 5
    total = n_shards * SHARD_WIDTH
    # row 1: very sparse (COO path); row 2: dense storage (bulk);
    # row 3: between the COO threshold and HostRow's densify cutoff
    # (sparse storage, dense upload); row 4 only in shard 0.
    f.import_bits(np.ones(300, dtype=np.uint64),
                  rng.choice(total, 300, replace=False))
    cols2 = rng.choice(total, 120_000, replace=False)
    f.import_bits(np.full(len(cols2), 2, dtype=np.uint64), cols2)
    cols3 = rng.choice(SHARD_WIDTH, 5000, replace=False)  # shard 0 only
    f.import_bits(np.full(len(cols3), 3, dtype=np.uint64), cols3)
    f.set_bit(4, 17)

    dense_p = MeshPlanner(h, make_mesh())
    dense_p._sparse_upload_enabled = lambda: False  # pin: on a TPU host
    # the default gate would make this a sparse==sparse comparison
    sparse_p = MeshPlanner(h, make_mesh())
    sparse_p._sparse_upload_enabled = lambda: True
    shards = tuple(range(n_shards))
    for row in (1, 2, 3, 4, 9):  # 9: absent row
        want = np.asarray(dense_p._stack_rows(idx, "f", "standard", row,
                                              shards))
        got = np.asarray(sparse_p._stack_rows(idx, "f", "standard", row,
                                              shards))
        assert got.shape == want.shape
        assert np.array_equal(got, want), row

    # End to end: counts agree with the scalar executor.
    e = Executor(h, planner=sparse_p, result_cache=False)
    s = Executor(h)
    for q in ("Count(Row(f=1))", "Count(Intersect(Row(f=2), Row(f=3)))",
              "Count(Union(Row(f=1), Row(f=4)))"):
        (got,) = e.execute("su", q, cache=False)
        (want,) = s.execute("su", q, cache=False)
        assert got == want, q
