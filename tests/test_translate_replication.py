"""Cluster key-translation replication (reference translate.go:93,
holder.go:785-878, http/translator.go): the coordinator is the sole id
allocator; every node resolves the same key to the same id no matter
which node receives the query or import, and replicas catch up via the
entry stream."""

import json
import socket
import urllib.request

import numpy as np
import pytest

from pilosa_tpu.cluster.harness import LocalCluster
from pilosa_tpu.core.field import FieldOptions
from pilosa_tpu.core.index import IndexOptions


def _mk_keyed_cluster(n=3):
    lc = LocalCluster(n)
    lc.create_index("k", IndexOptions(keys=True))
    lc.create_field("k", "f", FieldOptions(keys=True))
    return lc


def test_same_key_same_id_any_node():
    lc = _mk_keyed_cluster()
    # Allocate the same keys through different nodes: ids must agree.
    ids = [lc.nodes[i].translator("k", "f", ["alpha", "beta"])
           for i in range(3)]
    assert ids[0] == ids[1] == ids[2]
    # Index (column) keys too.
    cids = [lc.nodes[i].translator("k", None, ["c1", "c2"]) for i in range(3)]
    assert cids[0] == cids[1] == cids[2]
    # Distinct keys get distinct ids even when allocated via
    # different nodes.
    a = lc.nodes[1].translator("k", "f", ["gamma"])[0]
    b = lc.nodes[2].translator("k", "f", ["delta"])[0]
    assert a != b


def test_query_via_any_node_consistent():
    lc = _mk_keyed_cluster()
    # Writes through different nodes using keys.
    lc.nodes[1].executor.execute("k", 'Set("c1", f="r1")')
    lc.nodes[2].executor.execute("k", 'Set("c2", f="r1")')
    lc.nodes[0].executor.execute("k", 'Set("c3", f="r2")')
    for i in range(3):
        (cnt,) = lc.nodes[i].executor.execute("k", 'Count(Row(f="r1"))')
        assert cnt == 2, (i, cnt)
        (cnt2,) = lc.nodes[i].executor.execute("k", 'Count(Row(f="r2"))')
        assert cnt2 == 1, (i, cnt2)


def test_reverse_translation_after_sync():
    lc = _mk_keyed_cluster()
    lc.nodes[1].executor.execute("k", 'Set("c9", f="r9")')
    lc.sync_translation()
    # Every node can reverse-translate ids allocated elsewhere.
    for cn in lc.nodes:
        idx = cn.holder.index("k")
        f = idx.field("f")
        rid = f.translate_store.translate_key("r9", create=False)
        cid = idx.translate_store.translate_key("c9", create=False)
        assert rid is not None and cid is not None
        assert f.translate_store.translate_id(rid) == "r9"
        assert idx.translate_store.translate_id(cid) == "c9"
    # Row() keys resolve on a node that never saw the write.
    (row,) = lc.nodes[2].executor.execute("k", 'Row(f="r9")')
    assert row.keys == ["c9"]


def test_coordinator_down_existing_keys_still_resolve():
    lc = _mk_keyed_cluster()
    lc.nodes[1].translator("k", "f", ["seen"])
    lc.down("node0")  # coordinator gone
    # Known key resolves from the local replica copy.
    assert lc.nodes[1].translator("k", "f", ["seen"]) is not None
    # Unknown key cannot be allocated without the authority.
    with pytest.raises(ConnectionError):
        lc.nodes[1].translator("k", "f", ["never-seen"])


def test_http_cluster_translation():
    """Two ServerNodes over real HTTP: keyed writes via the
    non-coordinator agree with the coordinator."""
    from pilosa_tpu.server.node import ServerNode

    ports = []
    for _ in range(2):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        ports.append(s.getsockname()[1])
        s.close()
    addrs = [f"127.0.0.1:{p}" for p in ports]
    nodes = [ServerNode(bind=a, peers=[x for x in addrs if x != a],
                        use_planner=False) for a in addrs]
    for n in nodes:
        n.open()
    try:
        # Coordinator = sorted-first address.
        coord = min(addrs)
        other = max(addrs)
        coord_node = next(n for n in nodes if n.id == coord)
        other_node = next(n for n in nodes if n.id == other)

        def post(addr, path, body=""):
            r = urllib.request.Request(f"http://{addr}{path}",
                                       data=body.encode(), method="POST")
            return json.loads(urllib.request.urlopen(r, timeout=10).read()
                              or b"{}")

        post(other, "/index/k", json.dumps({"options": {"keys": True}}))
        post(other, "/index/k/field/f",
             json.dumps({"options": {"keys": True}}))
        # Writes with keys through BOTH nodes.
        post(other, "/index/k/query", 'Set("c1", f="r1")')
        post(coord, "/index/k/query", 'Set("c2", f="r1")')
        for addr in addrs:
            got = post(addr, "/index/k/query", 'Count(Row(f="r1"))')
            assert got == {"results": [2]}, (addr, got)
        # The id maps agree between the nodes for the shared keys.
        f_coord = coord_node.holder.index("k").field("f")
        f_other = other_node.holder.index("k").field("f")
        rid = f_coord.translate_store.translate_key("r1", create=False)
        assert rid is not None
        assert f_other.translate_store.translate_key(
            "r1", create=False) == rid
        # Entry-stream catch-up over HTTP.
        from pilosa_tpu.cluster.translate_sync import sync_translation
        coord_node.api.translate_keys("k", "f", ["coord-only"])
        applied = sync_translation(other_node.holder, other_node.cluster,
                                   other_node.cluster.client)
        assert applied >= 1
        assert f_other.translate_store.translate_key(
            "coord-only", create=False) == f_coord.translate_store. \
            translate_key("coord-only", create=False)
    finally:
        for n in nodes:
            try:
                n.close()
            except Exception:
                pass


def test_watermark_pull_fills_gaps():
    """ADVICE r2: apply_entries advances _next past unseen ids, so pulling
    entries_since(max_id()) skips coordinator entries with smaller ids.
    The contiguous replication watermark must not."""
    from pilosa_tpu.core.translate import TranslateStore

    coord = TranslateStore()
    for k in ("a", "b", "c", "d"):   # ids 1..4
        coord.translate_key(k)
    replica = TranslateStore()
    # Replica first learns only id 4 (e.g. via a query touching "d").
    replica.apply_entries([(4, "d")])
    assert replica.max_id() == 4          # _next raced ahead
    assert replica.replication_watermark() == 0
    entries = coord.entries_since(replica.replication_watermark())
    replica.apply_entries(entries)
    for k in ("a", "b", "c", "d"):
        assert replica.translate_key(k, create=False) == \
            coord.translate_key(k, create=False)
    assert replica.replication_watermark() == 4
