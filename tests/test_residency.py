"""Device residency tests: container-classed stacks (exec/residency)
and the pipelined prefetch miss path (parallel/prefetch).

The contract mirrors the reference's roaring container taxonomy tests
(roaring_internal_test.go: array/bitmap conversions are bit-exact):
the packed representation must be *bit-identical* to dense through
every query family, proven generatively over seeded random data, while
the oversubscription drill proves the prefetch pipeline keeps the
query path free of synchronous uploads under eviction churn.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from pilosa_tpu.config import SHARD_WIDTH, WORDS_PER_SHARD
from pilosa_tpu.core import FieldOptions, Holder
from pilosa_tpu.core.field import FIELD_TYPE_INT
from pilosa_tpu.exec import Executor
from pilosa_tpu.exec import residency
from pilosa_tpu.ops import bitops
from pilosa_tpu.parallel import MeshPlanner, make_mesh
from pilosa_tpu.parallel import prefetch as prefetch_mod


@pytest.fixture(scope="module")
def mesh():
    assert len(jax.devices()) == 8, "conftest must provide 8 virtual devices"
    return make_mesh()


# -- representation policy ---------------------------------------------------


def test_pack_width_pow2_buckets():
    assert residency.pack_width(0) == residency.MIN_PACK_WIDTH
    assert residency.pack_width(8) == 8
    assert residency.pack_width(9) == 16
    assert residency.pack_width(250) == 256
    assert residency.pack_width(256) == 256
    assert residency.pack_width(257) == 512


def test_choose_class_per_mode(monkeypatch):
    lo, hi = 100, SHARD_WIDTH // 2          # sparse vs pathological rows
    mid = WORDS_PER_SHARD // residency.AUTO_RATIO + 1   # auto's boundary
    monkeypatch.setenv("PILOSA_TPU_RESIDENCY_PACKED", "off")
    assert residency.choose_class(lo) == residency.DENSE
    monkeypatch.setenv("PILOSA_TPU_RESIDENCY_PACKED", "auto")
    assert residency.choose_class(lo) == residency.PACKED
    assert residency.choose_class(mid) == residency.DENSE
    monkeypatch.setenv("PILOSA_TPU_RESIDENCY_PACKED", "on")
    assert residency.choose_class(mid) == residency.PACKED
    # high cardinality falls back to dense in EVERY mode
    for m in ("on", "auto", "off"):
        monkeypatch.setenv("PILOSA_TPU_RESIDENCY_PACKED", m)
        assert residency.choose_class(hi) == residency.DENSE, m


def test_mode_knob_validates_and_env_wins(monkeypatch):
    with pytest.raises(ValueError):
        residency.set_mode("sometimes")
    with pytest.raises(ValueError):
        prefetch_mod.set_mode("maybe")
    try:
        monkeypatch.delenv("PILOSA_TPU_RESIDENCY_PACKED", raising=False)
        residency.set_mode("on")
        assert residency.mode() == "on"
        monkeypatch.setenv("PILOSA_TPU_RESIDENCY_PACKED", "off")
        assert residency.mode() == "off"          # env beats server knob
        monkeypatch.setenv("PILOSA_TPU_RESIDENCY_PACKED", "bogus")
        assert residency.mode() == "on"           # junk env is ignored
    finally:
        residency.set_mode("auto")


# -- kernel variants vs dense references -------------------------------------


def _random_packed(rng, s=4, k=64, fill=0.6):
    """A [s, k] sorted-index stack with sentinel padding, plus the
    equivalent dense [s, W] uint32 planes built independently."""
    mat = np.full((s, k), residency.SENTINEL, dtype=np.int32)
    dense = np.zeros((s, WORDS_PER_SHARD), dtype=np.uint32)
    for i in range(s):
        n = int(rng.integers(0, int(k * fill) + 1))
        pos = np.sort(rng.choice(SHARD_WIDTH, n, replace=False))
        mat[i, :n] = pos
        dense[i, pos >> 5] |= np.uint32(1) << (pos & 31).astype(np.uint32)
    return jnp.asarray(mat), jnp.asarray(dense)


def test_packed_expand_bit_exact(rng):
    idxs, dense = _random_packed(rng)
    out = np.asarray(residency.packed_expand(idxs))
    np.testing.assert_array_equal(out, dense)


def test_packed_count_matches_dense_popcount(rng):
    idxs, dense = _random_packed(rng)
    got = np.asarray(residency.packed_count(idxs))
    want = np.asarray(bitops.count(jnp.asarray(dense)))
    np.testing.assert_array_equal(got, want)


def test_packed_and_dense_count_matches(rng):
    idxs, dense_a = _random_packed(rng)
    _, dense_b = _random_packed(rng, fill=0.9)
    got = np.asarray(residency.packed_and_dense_count(idxs,
                                                      jnp.asarray(dense_b)))
    want = np.asarray(bitops.intersection_count(jnp.asarray(dense_a),
                                                jnp.asarray(dense_b)))
    np.testing.assert_array_equal(got, want)


def test_packed_pair_count_matches(rng):
    a_idx, a_dense = _random_packed(rng)
    b_idx, b_dense = _random_packed(rng, k=32)
    got = np.asarray(residency.packed_pair_count(a_idx, b_idx))
    want = np.asarray(bitops.intersection_count(jnp.asarray(a_dense),
                                                jnp.asarray(b_dense)))
    np.testing.assert_array_equal(got, want)


def test_kernel_lookup_raises_on_unknown_pair():
    assert residency.kernel(residency.PACKED, "count") is residency.packed_count
    with pytest.raises(KeyError, match="no 'count' kernel.*'run'"):
        residency.kernel("run", "count")


# -- generative packed-vs-dense equivalence over query families ---------------

N_SHARDS = 4

#: every planner query family, with trees that route each packed
#: kernel: pair_count (packed∧packed), and_count (packed∧dense),
#: expand (unions/differences/NOT and every aggregate filter).
EQ_QUERIES = [
    "Count(Row(f=0))",
    "Count(Row(f=4))",                                   # dense leaf
    "Count(Intersect(Row(f=1), Row(g=2)))",              # packed ∧ packed
    "Count(Intersect(Row(f=1), Row(f=4)))",              # packed ∧ dense
    "Count(Intersect(Row(f=4), Row(g=5)))",              # dense ∧ dense
    "Count(Union(Row(f=0), Row(g=0), Row(f=3)))",
    "Count(Difference(Row(f=4), Row(g=1)))",
    "Count(Xor(Row(f=2), Row(g=2)))",
    "Count(Not(Row(f=1)))",
    "Count(Intersect(Union(Row(f=0), Row(f=1)), Not(Row(g=3))))",
    "Row(f=1)",
    "TopN(f, n=4)",
    "TopN(f, Row(g=1), n=3)",
    "Sum(Row(f=1), field=v)",
    "Sum(Intersect(Row(f=1), Row(g=2)), field=v)",
    "Min(Row(f=0), field=v)",
    "Max(Row(f=0), field=v)",
    "GroupBy(Rows(f), Rows(g))",
]


def _seed_mixed(idx, rng):
    """Rows 0-3 sparse (packable), rows 4-5 heavy (auto falls back to
    dense; ``on`` packs row 4's wave only if it fits MAX_PACK_WIDTH)."""
    f = idx.create_field("f")
    g = idx.create_field("g")
    v = idx.create_field("v",
                         FieldOptions(type=FIELD_TYPE_INT, min=-500, max=500))
    total = N_SHARDS * SHARD_WIDTH
    for field in (f, g):
        for r in range(4):
            n = int(rng.integers(50, 2000))
            field.import_bits(np.full(n, r), rng.integers(0, total, n))
        for r in (4, 5):
            field.import_bits(np.full(60000, r),
                              rng.integers(0, total, 60000))
    vcols = rng.choice(total, 3000, replace=False)
    v.import_values(vcols.tolist(),
                    rng.integers(-500, 500, len(vcols)).tolist())
    idx.add_existence(np.arange(0, total, 5))
    return f, g, v


def _run_suite(h, mesh, mode_name, monkeypatch):
    monkeypatch.setenv("PILOSA_TPU_RESIDENCY_PACKED", mode_name)
    planner = MeshPlanner(h, mesh)
    e = Executor(h, planner=planner, result_cache=False)
    shards = list(range(N_SHARDS))
    try:
        out = [e.execute("rq", q, shards=shards) for q in EQ_QUERIES]
        classes = {k[6] for k in planner._stack_cache}
        n_packed = sum(1 for k in planner._stack_cache
                       if k[6] == residency.PACKED)
        cls_bytes = planner.cache_stats()["class_bytes"]
    finally:
        planner.close()
    return out, classes, n_packed, cls_bytes


@pytest.mark.parametrize("seed", [
    0,
    pytest.param(1, marks=pytest.mark.slow),
    pytest.param(2, marks=pytest.mark.slow),
])
def test_packed_dense_bit_equivalence_generative(mesh, monkeypatch, seed):
    """The acceptance gate: for every query family, packed execution is
    bit-identical to dense, across auto and forced-on policies."""
    h = Holder()
    idx = h.create_index("rq")
    _seed_mixed(idx, np.random.default_rng(seed))
    want, classes, _, _ = _run_suite(h, mesh, "off", monkeypatch)
    assert classes <= {residency.DENSE}
    for mode_name in ("auto", "on"):
        got, classes, n_packed, cls_bytes = _run_suite(
            h, mesh, mode_name, monkeypatch)
        assert got == want, mode_name
        # the packed path actually ran
        assert residency.PACKED in classes, mode_name
        assert cls_bytes[residency.PACKED] > 0, mode_name
        if mode_name == "auto":
            # auto only packs stacks at least AUTO_RATIO× under dense
            assert cls_bytes[residency.PACKED] <= (
                n_packed * residency.dense_nbytes(N_SHARDS)
                // residency.AUTO_RATIO), mode_name


def test_mutation_then_requery_stays_equivalent(mesh, monkeypatch):
    """Epoch bumps must invalidate packed stacks AND replan leaves
    whose class flips (sparse row grown past the auto threshold)."""
    h = Holder()
    idx = h.create_index("rq")
    f, g, _ = _seed_mixed(idx, np.random.default_rng(7))
    queries = EQ_QUERIES[:10]

    def sweep(mode_name, executor):
        monkeypatch.setenv("PILOSA_TPU_RESIDENCY_PACKED", mode_name)
        shards = list(range(N_SHARDS))
        return [executor.execute("rq", q, shards=shards) for q in queries]

    dense_p = MeshPlanner(h, mesh)
    packed_p = MeshPlanner(h, mesh)
    try:
        e_dense = Executor(h, planner=dense_p, result_cache=False)
        e_packed = Executor(h, planner=packed_p, result_cache=False)
        assert sweep("auto", e_packed) == sweep("off", e_dense)

        # mutate: grow row 1 past auto's packing threshold (class flip
        # → plan revalidation must drop its cached programs), touch a
        # heavy row, and clear bits from row 0 (stays packed).
        total = N_SHARDS * SHARD_WIDTH
        rng = np.random.default_rng(8)
        f.import_bits(np.full(30000, 1), rng.integers(0, total, 30000))
        g.import_bits(np.full(500, 5), rng.integers(0, total, 500))
        for col in np.asarray(f.row(0).columns()[:20]):
            f.clear_bit(0, int(col))

        assert sweep("auto", e_packed) == sweep("off", e_dense)
    finally:
        dense_p.close()
        packed_p.close()


def test_auto_high_cardinality_rows_stay_dense(mesh, monkeypatch):
    monkeypatch.setenv("PILOSA_TPU_RESIDENCY_PACKED", "auto")
    h = Holder()
    idx = h.create_index("hc")
    f = idx.create_field("f")
    total = N_SHARDS * SHARD_WIDTH
    rng = np.random.default_rng(3)
    f.import_bits(np.full(300, 0), rng.integers(0, total, 300))       # sparse
    f.import_bits(np.full(40000, 1), rng.integers(0, total, 40000))   # heavy
    planner = MeshPlanner(h, mesh)
    e = Executor(h, planner=planner, result_cache=False)
    shards = list(range(N_SHARDS))
    try:
        e.execute("hc", "Count(Row(f=0))", shards=shards)
        e.execute("hc", "Count(Row(f=1))", shards=shards)
        by_row = {k[4]: k[6] for k in planner._stack_cache}
        assert by_row[0] == residency.PACKED
        assert by_row[1] == residency.DENSE    # fell back, as documented
        st = planner.cache_stats()
        assert st["residency_mode"] == "auto"
        assert sum(st["class_bytes"].values()) == st["bytes"]
    finally:
        planner.close()


# -- oversubscription drill: the pipelined miss path --------------------------


def test_oversubscribed_prefetch_no_sync_uploads(mesh, monkeypatch):
    """Working set > device budget with prefetch on: eviction churns,
    yet every query-thread miss rendezvouses with an inflight upload —
    zero synchronous uploads on the query path (the BENCH_r05 cliff)."""
    monkeypatch.setenv("PILOSA_TPU_RESIDENCY_PACKED", "off")  # dense bytes
    monkeypatch.setenv("PILOSA_TPU_PREFETCH", "on")
    h = Holder()
    idx = h.create_index("ov")
    f = idx.create_field("f")
    n_shards = 8
    total = n_shards * SHARD_WIDTH
    rng = np.random.default_rng(5)
    for r in range(6):
        f.import_bits(np.full(2000, r), rng.integers(0, total, 2000))
    stack_bytes = residency.dense_nbytes(8)
    planner = MeshPlanner(h, mesh, max_cache_bytes=3 * stack_bytes)
    e = Executor(h, planner=planner, result_cache=False)
    shards = list(range(n_shards))
    try:
        for _ in range(2):                      # 12 misses through 3 slots
            for r in range(6):
                e.execute("ov", f"Count(Row(f={r}))", shards=shards)
        assert planner.cache_stats()["evictions"] > 0
        dbg = planner.prefetcher.debug()
        assert dbg["sync_misses"] == 0
        assert dbg["hits"] > 0
        assert dbg["completed"] == dbg["scheduled"] >= 6
        assert dbg["inflight"] == 0 and dbg["queued"] == 0
    finally:
        planner.close()


def test_prefetch_off_counts_sync_misses(mesh, monkeypatch):
    monkeypatch.setenv("PILOSA_TPU_PREFETCH", "off")
    h = Holder()
    idx = h.create_index("sy")
    f = idx.create_field("f")
    f.import_bits(np.full(100, 0), np.arange(100))
    planner = MeshPlanner(h, mesh)
    e = Executor(h, planner=planner, result_cache=False)
    try:
        e.execute("sy", "Count(Row(f=0))", shards=[0])
        dbg = planner.prefetcher.debug()
        assert dbg["scheduled"] == 0
        assert dbg["sync_misses"] >= 1
    finally:
        planner.close()
