"""Partition tolerance: quorum self-fencing, SWIM-style indirect
probes, fencing tokens on coordinator broadcasts, fenced coordinator
duties, and split-brain heal convergence — all over the deterministic
LocalCluster harness (pair faults on the shared transport, failure-
detector sweeps run by hand)."""

import pytest

from pilosa_tpu.cluster.cluster import Cluster
from pilosa_tpu.cluster.harness import LocalCluster
from pilosa_tpu.cluster.node import URI, Node
from pilosa_tpu.cluster.resize import check_nodes
from pilosa_tpu.config import SHARD_WIDTH
from pilosa_tpu.obs.stats import MemoryStats


def _ring(n: int, local: int = 0, replica_n: int = 1) -> Cluster:
    """Bare membership view (no transport): enough for the fence and
    token state machines, which are pure Cluster-side logic."""
    from pilosa_tpu.cluster.cluster import STATE_NORMAL
    nodes = [Node(id=f"node{i}", uri=URI(host="localhost", port=10101 + i),
                  is_coordinator=(i == 0)) for i in range(n)]
    c = Cluster(local_id=f"node{local}", nodes=nodes, replica_n=replica_n)
    c.set_state(STATE_NORMAL)
    c.stats = MemoryStats()
    return c


# -- quorum fence state machine -------------------------------------------


def test_observe_quorum_fences_minority_and_unfences_on_majority():
    c = _ring(5)
    fired = []
    c.on_unfence = lambda: fired.append(1)

    assert c.observe_quorum(3, 5) is False
    assert not c.fenced

    # Losing the majority fences; staying fenced doesn't re-count.
    assert c.observe_quorum(2, 5) is True
    assert c.fenced
    assert c.stats.counter_value("cluster.fenced") == 1
    assert c.observe_quorum(1, 5) is True
    assert c.stats.counter_value("cluster.fenced") == 1
    assert not fired

    # Regaining the majority un-fences and fires the rejoin-repair hook.
    assert c.observe_quorum(3, 5) is False
    assert not c.fenced
    assert c.stats.counter_value("cluster.unfenced") == 1
    assert fired == [1]

    # Exactly half is NOT a strict majority: 3 of 6 stays fenced.
    c.observe_quorum(2, 6)
    assert c.fenced
    assert c.observe_quorum(3, 6) is True


def test_quorum_fence_exempts_rings_smaller_than_three():
    # With 2 nodes a single peer loss has no majority on either side;
    # fencing would turn every degraded-replica situation into an
    # outage, so small rings never fence.
    c2 = _ring(2)
    assert c2.observe_quorum(1, 2) is False
    assert not c2.fenced
    c1 = _ring(1)
    assert c1.observe_quorum(1, 1) is False
    # 3 nodes is the smallest ring where the fence engages.
    c3 = _ring(3)
    assert c3.observe_quorum(1, 3) is True


def test_fencing_token_is_monotonic_and_stale_tokens_rejected():
    c = _ring(3)
    c.topology_version = 4
    assert c.fencing_token() == 4

    # No token (peer-to-peer / legacy senders) and current-or-newer
    # tokens pass; older-than-our-topology tokens are rejected.
    assert c.check_fencing_token({}) is True
    assert c.check_fencing_token({"fencingToken": 4}) is True
    assert c.check_fencing_token({"fencingToken": 7}) is True
    assert c.check_fencing_token({"fencingToken": 3}) is False
    assert c.stats.counter_value("cluster.staleTokenRejected") == 1

    # A takeover/commit bumps the topology: the deposed coordinator's
    # previously-valid token goes stale.
    c.topology_version += 1
    assert c.check_fencing_token({"fencingToken": 4}) is False
    assert c.stats.counter_value("cluster.staleTokenRejected") == 2


# -- fencing tokens on coordinator broadcasts -----------------------------


def test_stale_fencing_token_rejects_resize_begin():
    from pilosa_tpu.cluster.resize import apply_resize_begin
    lc = LocalCluster(3, replica_n=2)
    peer = lc[1]
    peer.cluster.stats = MemoryStats()
    peer.cluster.topology_version = 5

    begin = {"type": "resize-begin", "job": "stale-job",
             "coordinator": {"id": "node0"},
             "nodes": [n.to_json() for n in peer.cluster.nodes],
             "replicaN": 2, "partitionN": peer.cluster.partition_n,
             "fencingToken": 4}
    apply_resize_begin(peer.cluster, begin)
    assert peer.cluster.migration is None
    assert peer.cluster.stats.counter_value(
        "cluster.staleTokenRejected") == 1

    # The same begin with a current token installs the table.
    begin["fencingToken"] = 5
    apply_resize_begin(peer.cluster, begin)
    assert peer.cluster.migration is not None
    assert peer.cluster.migration.job_id == "stale-job"


def test_stale_fencing_token_rejects_index_dirty_coordination():
    lc = LocalCluster(2, replica_n=2)
    lc.create_index("pt")
    lc.create_field("pt", "f")
    receiver = lc[1]
    receiver.cluster.stats = MemoryStats()
    receiver.cluster.topology_version = 3
    idx = receiver.holder.index("pt")
    before = idx.epoch.value

    receiver.handle_message({"type": "index-dirty", "index": "pt",
                             "sender": "node0", "fencingToken": 2})
    assert idx.epoch.value == before
    assert receiver.cluster.stats.counter_value(
        "cluster.staleTokenRejected") == 1

    # Current token applies (and an untokened legacy sender would too).
    receiver.handle_message({"type": "index-dirty", "index": "pt",
                             "sender": "node0", "fencingToken": 3})
    assert idx.epoch.value > before


# -- failure detector: indirect probes ------------------------------------


def test_indirect_probe_saves_suspect_in_asymmetric_partition():
    # node0 cannot reach node2, but node1 can: SWIM indirect
    # confirmation must keep node2 READY and count it reachable.
    lc = LocalCluster(3, replica_n=2)
    a = lc[0]
    a.cluster.stats = MemoryStats()
    lc.block_link(0, 2)

    changed = check_nodes(a.cluster, a.cluster.client, retries=1,
                          discover=False)
    assert changed == []
    assert a.cluster.node_by_id("node2").state != "DOWN"
    obs = a.cluster.membership_log["node2"]
    assert obs["lastProbeOk"] is True
    assert obs["lastProbeDirect"] is False
    assert obs["indirect"] == {"node1": True}
    # Indirectly-alive peers count toward quorum: no fence.
    assert not a.cluster.fenced
    assert a.cluster.stats.counter_value("cluster.nodeDown") == 0


def test_indirect_probes_confirm_down_then_nodeup_on_heal():
    lc = LocalCluster(3, replica_n=2)
    a = lc[0]
    a.cluster.stats = MemoryStats()
    lc.client.down.add("node2")

    changed = check_nodes(a.cluster, a.cluster.client, retries=1,
                          discover=False)
    assert changed == ["node2"]
    assert a.cluster.node_by_id("node2").state == "DOWN"
    obs = a.cluster.membership_log["node2"]
    assert obs["lastProbeOk"] is False
    assert obs["lastProbeDirect"] is False
    assert obs["indirect"] == {"node1": False}
    assert a.cluster.stats.counter_value("cluster.nodeDown") == 1
    # Majority of 3 still reachable (self + node1): no self-fence.
    assert not a.cluster.fenced

    # An already-DOWN corpse is not re-confirmed every sweep.
    check_nodes(a.cluster, a.cluster.client, retries=1, discover=False)
    assert a.cluster.membership_log["node2"]["indirect"] == {}

    lc.client.down.discard("node2")
    changed = check_nodes(a.cluster, a.cluster.client, retries=1,
                          discover=False)
    assert changed == ["node2"]
    assert a.cluster.node_by_id("node2").state == "READY"
    assert a.cluster.stats.counter_value("cluster.nodeUp") == 1


def test_indirect_probe_degenerate_two_node_ring_has_no_intermediaries():
    lc = LocalCluster(2, replica_n=2)
    a = lc[0]
    a.cluster.stats = MemoryStats()
    lc.client.down.add("node1")

    changed = check_nodes(a.cluster, a.cluster.client, retries=1,
                          discover=False)
    assert changed == ["node1"]
    assert a.cluster.membership_log["node1"]["indirect"] == {}
    # 2-node rings are exempt from the quorum fence.
    assert not a.cluster.fenced


# -- transport pair faults ------------------------------------------------


def test_partition_pair_faults_are_directional():
    lc = LocalCluster(3, replica_n=2)
    lc.block_link("node0", "node2")
    n0_view_of_2 = lc[0].cluster.node_by_id("node2")
    n2_view_of_0 = lc[2].cluster.node_by_id("node0")

    with pytest.raises(ConnectionError):
        lc[0].cluster.client.probe(n0_view_of_2)
    # The reverse direction is untouched (asymmetric by construction).
    lc[2].cluster.client.probe(n2_view_of_0)

    lc.heal_partition()
    lc[0].cluster.client.probe(n0_view_of_2)


def test_minority_island_self_fences_while_majority_keeps_it_ready():
    # Cut ONLY node2's outbound links: node2 sees nobody (fences), but
    # the majority still reaches node2 directly, so no DOWN churn.
    lc = LocalCluster(3, replica_n=2)
    lc.block_link(2, 0)
    lc.block_link(2, 1)
    lc.check_all_nodes()

    assert lc[2].cluster.fenced
    assert not lc[0].cluster.fenced and not lc[1].cluster.fenced
    assert lc[0].cluster.node_by_id("node2").state != "DOWN"
    assert lc[1].cluster.node_by_id("node2").state != "DOWN"

    lc.heal_partition()
    lc.check_all_nodes()
    assert not lc[2].cluster.fenced


def test_split_brain_partition_fences_minority_majority_serves_quorum():
    lc = LocalCluster(5, replica_n=3)
    for cn in lc.nodes:
        cn.cluster.stats = MemoryStats()
    lc.create_index("pt")
    lc.create_field("pt", "f")
    for col in (1, SHARD_WIDTH + 2, 2 * SHARD_WIDTH + 3):
        lc.query("pt", f"Set({col}, f=1)")

    lc.partition([3, 4])
    lc.check_all_nodes()

    # Each side discovered the split on its own: the 2-node island
    # fenced itself, the 3-node majority did not.
    assert lc[3].cluster.fenced and lc[4].cluster.fenced
    assert not any(lc[i].cluster.fenced for i in (0, 1, 2))
    assert lc[3].cluster.stats.counter_value("cluster.fenced") == 1
    # Majority placement (replica 3 of 5, consecutive) always keeps at
    # least one live owner per shard: reads keep flowing.
    assert lc.query("pt", "Count(Row(f=1))")[0] == 3

    lc.heal_partition()
    lc.check_all_nodes()
    assert not any(cn.cluster.fenced for cn in lc.nodes)
    assert lc[3].cluster.stats.counter_value("cluster.unfenced") == 1
    assert lc.query("pt", "Count(Row(f=1))")[0] == 3


# -- API fence gate -------------------------------------------------------


def test_api_fence_refuses_traffic_allows_opted_in_stale_reads():
    from pilosa_tpu.errors import ClusterFencedError
    from pilosa_tpu.server.api import API

    lc = LocalCluster(3, replica_n=2)
    a = lc[0]
    api = API(a.holder, a.executor, cluster=a.cluster)
    api.create_index("fz")
    api.create_field("fz", "f")
    api.query("fz", "Set(1, f=1)")

    a.cluster.fenced = True
    with pytest.raises(ClusterFencedError) as ei:
        api.query("fz", "Count(Row(f=1))")
    assert ei.value.retry_after > 0
    with pytest.raises(ClusterFencedError):
        api.create_index("fz2")
    # Internal traffic (peer forwards, repair pushes from the majority)
    # is exempt — it is how the fence heals.
    api._validate("import", internal=True)

    # Operator opt-in: reads (and only reads) flow while fenced.
    a.cluster.fence_stale_reads = True
    api.query("fz", "Count(Row(f=1))")
    with pytest.raises(ClusterFencedError):
        api.create_index("fz2")

    a.cluster.fenced = False
    api.create_index("fz2")


# -- fenced coordinator duties --------------------------------------------


def test_backup_scheduler_fence_suspends_capture_single_ticker():
    from pilosa_tpu.backup.scheduler import (
        SKIP_FENCED,
        SKIP_NOT_COORDINATOR,
        BackupScheduler,
    )

    lc = LocalCluster(3, replica_n=2)
    stats = MemoryStats()
    fenced_coord = BackupScheduler(
        holder=lc[0].holder, cluster=lc[0].cluster,
        client=lc[0].cluster.client, store=None, archive=None,
        interval=3600.0, node_id="node0", stats=stats)
    lc[0].cluster.fenced = True
    assert fenced_coord.run_once(force=True) == SKIP_FENCED
    assert stats.counter_value("backup.scheduler.skippedFenced") == 1
    assert fenced_coord.last_status == SKIP_FENCED

    # Non-coordinators skip regardless: a fenced coordinator plus
    # deferring peers means at most one scheduler ever captures.
    peer = BackupScheduler(
        holder=lc[2].holder, cluster=lc[2].cluster,
        client=lc[2].cluster.client, store=None, archive=None,
        interval=3600.0, node_id="node2", stats=MemoryStats())
    assert peer.run_once(force=True) == SKIP_NOT_COORDINATOR


def test_retention_prune_fence_gate_deletes_nothing():
    from pilosa_tpu.backup.retention import prune_archive

    stats = MemoryStats()
    # fence=True aborts before the archive is touched at all.
    summary = prune_archive(None, 1, stats=stats, fence=lambda: True)
    assert summary["aborted"] == "fenced"
    assert summary["pruned"] == 0 and summary["victims"] == []
    assert stats.counter_value("backup.retention.fenced") == 1


def test_resize_job_refuses_to_run_while_fenced():
    from pilosa_tpu.cluster.resize import ResizeJob

    lc = LocalCluster(3, replica_n=2)
    coord = lc[0]
    coord.cluster.fenced = True
    job = ResizeJob(coord.cluster, coord.holder, coord.cluster.client)
    new_ring = [Node(id=n.id, uri=n.uri, is_coordinator=n.is_coordinator)
                for n in coord.cluster.nodes]
    assert job.run(new_ring) == "FAILED"
    assert coord.cluster.migration is None


def test_scrub_fence_preserves_dirty_marks_and_refuses_push_repair():
    from pilosa_tpu.cluster.scrub import Scrubber

    class _StubQuarantine:
        @staticmethod
        def keys():
            return []

        @staticmethod
        def get(key):
            return None

    class _StubStore:
        quarantine = _StubQuarantine()

        @staticmethod
        def _all_keys():
            return []

    lc = LocalCluster(3, replica_n=2)
    a = lc[0]
    stats = MemoryStats()
    scr = Scrubber(a.holder, a.cluster, a.cluster.client, _StubStore(),
                   stats=stats)

    a.cluster.dirty_shards.mark("pt", 0)
    a.cluster.fenced = True
    scr.scrub_pass()
    # Fenced: the dirty mark survives as the rejoin repair's worklist...
    assert ("pt", 0) in a.cluster.dirty_shards.peek()
    # ...and a targeted push-repair is refused outright.
    assert scr._scrub_fragment(("pt", "f", "standard", 0)) is False
    assert stats.counter_value("integrity.scrubFenced") == 1

    a.cluster.fenced = False
    lc.create_index("pt")
    lc.create_field("pt", "f")
    scr.scrub_pass()
    assert ("pt", 0) not in a.cluster.dirty_shards.peek()


# -- heal convergence -----------------------------------------------------


def _fragment_digests(lc: LocalCluster) -> dict:
    """(index, field, view, shard) -> {node_id: block-checksum digest}
    across every node holding the fragment."""
    out: dict = {}
    for cn in lc.nodes:
        for iname in sorted(cn.holder.indexes):
            idx = cn.holder.index(iname)
            for fname, f in sorted(idx.fields.items()):
                for vname, v in sorted(f.views.items()):
                    for shard, frag in sorted(v.fragments.items()):
                        key = (iname, fname, vname, shard)
                        digest = tuple(sorted(
                            frag.checksum_blocks().items()))
                        out.setdefault(key, {})[cn.id] = digest
    return out


@pytest.mark.slow
def test_partition_heal_three_seed_bitwise_convergence():
    """Control run vs partitioned-then-healed run, same seeded write
    sequence: after heal + anti-entropy every replica must be
    bit-identical to the never-partitioned control."""
    import random as _random

    from pilosa_tpu.cluster.sync import HolderSyncer

    def run(seed: int, partitioned: bool) -> dict:
        lc = LocalCluster(3, replica_n=3)
        lc.create_index("pt")
        lc.create_field("pt", "f")
        rng = _random.Random(seed)

        def write():
            col = rng.randrange(4 * SHARD_WIDTH)
            row = rng.randrange(8)
            lc.query("pt", f"Set({col}, f={row})")

        for _ in range(40):
            write()
        if partitioned:
            lc.partition([2])
            # The sweep marks node2 DOWN on the majority (so writes
            # skip it and mark dirty) and fences the minority.
            lc.check_all_nodes()
            assert lc[2].cluster.fenced
            assert lc[0].cluster.node_by_id("node2").state == "DOWN"
        for _ in range(40):
            write()
        if partitioned:
            lc.heal_partition()
            lc.check_all_nodes()
            assert not lc[2].cluster.fenced
            # Two anti-entropy passes over every node: the first pushes
            # majority consensus onto the rejoined minority (creating
            # any fragments it never saw), the second settles.
            for _ in range(2):
                for cn in lc.nodes:
                    HolderSyncer(cn.holder, cn.cluster,
                                 cn.cluster.client).sync_holder()
        return _fragment_digests(lc)

    for seed in (1, 2, 3):
        control = run(seed, partitioned=False)
        healed = run(seed, partitioned=True)
        assert healed == control, f"seed {seed}: diverged after heal"
        for key, per_node in healed.items():
            assert len(set(per_node.values())) == 1, \
                f"seed {seed}: replicas of {key} diverged"
