"""Executor tests — cases modeled on reference executor_test.go.

Each test builds a Holder, writes via PQL Set()/direct imports, and checks
query results end to end through Executor.execute.
"""

import datetime as dt

import numpy as np
import pytest

from pilosa_tpu.config import SHARD_WIDTH
from pilosa_tpu.core import Holder, FieldOptions, IndexOptions, Row
from pilosa_tpu.core.field import (
    FIELD_TYPE_BOOL,
    FIELD_TYPE_INT,
    FIELD_TYPE_MUTEX,
    FIELD_TYPE_TIME,
)
from pilosa_tpu.errors import FieldNotFoundError, QueryError
from pilosa_tpu.exec import Executor, GroupCount, Pair, RowIdentifiers, ValCount


@pytest.fixture
def env():
    h = Holder()
    idx = h.create_index("i")
    return h, idx, Executor(h)


def q(e, src, index="i"):
    return e.execute(index, src)


# -- Set / Row / Count -----------------------------------------------------

def test_set_and_row(env):
    h, idx, e = env
    idx.create_field("f")
    assert q(e, "Set(100, f=1)") == [True]
    assert q(e, "Set(100, f=1)") == [False]  # already set
    (row,) = q(e, "Row(f=1)")
    assert row.columns().tolist() == [100]


def test_set_cross_shard(env):
    h, idx, e = env
    idx.create_field("f")
    cols = [3, SHARD_WIDTH + 5, 2 * SHARD_WIDTH + 7]
    for c in cols:
        q(e, f"Set({c}, f=9)")
    (row,) = q(e, "Row(f=9)")
    assert row.columns().tolist() == cols
    assert q(e, "Count(Row(f=9))") == [3]


def test_existence_tracked_on_set(env):
    h, idx, e = env
    idx.create_field("f")
    q(e, "Set(10, f=1) Set(20, f=2)")
    assert idx.existence_row().columns().tolist() == [10, 20]


def test_clear(env):
    h, idx, e = env
    idx.create_field("f")
    q(e, "Set(10, f=1)")
    assert q(e, "Clear(10, f=1)") == [True]
    assert q(e, "Clear(10, f=1)") == [False]
    assert q(e, "Count(Row(f=1))") == [0]


# -- combinators -----------------------------------------------------------

def test_intersect_union_difference_xor(env):
    h, idx, e = env
    idx.create_field("a")
    idx.create_field("b")
    a_cols = [1, 2, 3, SHARD_WIDTH + 1]
    b_cols = [2, 3, 4, SHARD_WIDTH + 2]
    for c in a_cols:
        q(e, f"Set({c}, a=1)")
    for c in b_cols:
        q(e, f"Set({c}, b=1)")
    (r,) = q(e, "Intersect(Row(a=1), Row(b=1))")
    assert r.columns().tolist() == [2, 3]
    (r,) = q(e, "Union(Row(a=1), Row(b=1))")
    assert r.columns().tolist() == sorted(set(a_cols) | set(b_cols))
    (r,) = q(e, "Difference(Row(a=1), Row(b=1))")
    assert r.columns().tolist() == [1, SHARD_WIDTH + 1]
    (r,) = q(e, "Xor(Row(a=1), Row(b=1))")
    assert r.columns().tolist() == [1, 4, SHARD_WIDTH + 1, SHARD_WIDTH + 2]


def test_not(env):
    h, idx, e = env
    idx.create_field("f")
    q(e, "Set(1, f=1) Set(2, f=1) Set(3, f=2)")
    (r,) = q(e, "Not(Row(f=1))")
    assert r.columns().tolist() == [3]
    (r,) = q(e, "Not(Union(Row(f=1), Row(f=2)))")
    assert r.columns().tolist() == []


def test_not_requires_existence(env):
    h, _, e = env
    idx2 = h.create_index("noex", IndexOptions(track_existence=False))
    idx2.create_field("f")
    with pytest.raises(QueryError):
        e.execute("noex", "Not(Row(f=1))")


def test_shift(env):
    h, idx, e = env
    idx.create_field("f")
    q(e, "Set(1, f=1) Set(5, f=1)")
    (r,) = q(e, "Shift(Row(f=1), n=2)")
    assert r.columns().tolist() == [3, 7]


# -- BSI / conditions ------------------------------------------------------

@pytest.fixture
def bsi_env(env):
    h, idx, e = env
    idx.create_field("v", FieldOptions(type=FIELD_TYPE_INT, min=-1100, max=1000))
    for col, val in {1: 10, 2: -20, 3: 30, 4: 0, SHARD_WIDTH + 1: 500}.items():
        q(e, f"Set({col}, v={val})")
    return h, idx, e


def test_set_int_value_and_conditions(bsi_env):
    h, idx, e = bsi_env
    (r,) = q(e, "Row(v > 5)")
    assert r.columns().tolist() == [1, 3, SHARD_WIDTH + 1]
    (r,) = q(e, "Row(v < 0)")
    assert r.columns().tolist() == [2]
    (r,) = q(e, "Row(v == 30)")
    assert r.columns().tolist() == [3]
    (r,) = q(e, "Row(v != 30)")
    assert r.columns().tolist() == [1, 2, 4, SHARD_WIDTH + 1]
    (r,) = q(e, "Row(v != null)")
    assert r.columns().tolist() == [1, 2, 3, 4, SHARD_WIDTH + 1]
    (r,) = q(e, "Row(v >< [0, 30])")
    assert r.columns().tolist() == [1, 3, 4]
    (r,) = q(e, "Row(-20 <= v < 30)")
    assert r.columns().tolist() == [1, 2, 4]


def test_condition_encompassing_range_returns_not_null(bsi_env):
    h, idx, e = bsi_env
    (r,) = q(e, "Row(v < 1000000)")  # past bit-depth max
    assert r.columns().tolist() == [1, 2, 3, 4, SHARD_WIDTH + 1]
    (r,) = q(e, "Row(v >= -1100)")
    assert r.columns().tolist() == [1, 2, 3, 4, SHARD_WIDTH + 1]


def test_sum_min_max(bsi_env):
    h, idx, e = bsi_env
    assert q(e, "Sum(field=v)") == [ValCount(520, 5)]
    assert q(e, "Min(field=v)") == [ValCount(-20, 1)]
    assert q(e, "Max(field=v)") == [ValCount(500, 1)]
    # with filter
    idx.create_field("f")
    q(e, "Set(1, f=1) Set(2, f=1)")
    assert q(e, "Sum(Row(f=1), field=v)") == [ValCount(-10, 2)]
    assert q(e, "Min(Row(f=1), field=v)") == [ValCount(-20, 1)]
    assert q(e, "Max(Row(f=1), field=v)") == [ValCount(10, 1)]


# -- MinRow / MaxRow -------------------------------------------------------

def test_min_max_row(env):
    h, idx, e = env
    idx.create_field("f")
    q(e, "Set(1, f=3) Set(2, f=7) Set(3, f=5)")
    assert q(e, "MinRow(field=f)") == [Pair(id=3, count=1)]
    assert q(e, "MaxRow(field=f)") == [Pair(id=7, count=1)]


# -- TopN ------------------------------------------------------------------

def test_top_n(env):
    h, idx, e = env
    f = idx.create_field("f")
    # row 0: 5 bits, row 1: 3 bits, row 2: 1 bit (spread over 2 shards)
    f.import_bits([0] * 5 + [1] * 3 + [2],
                  [0, 1, 2, SHARD_WIDTH, SHARD_WIDTH + 1,
                   10, 11, SHARD_WIDTH + 10, 20])
    (pairs,) = q(e, "TopN(f, n=2)")
    assert pairs == [Pair(id=0, count=5), Pair(id=1, count=3)]
    (pairs,) = q(e, "TopN(f)")
    assert pairs == [Pair(id=0, count=5), Pair(id=1, count=3), Pair(id=2, count=1)]


def test_top_n_with_src_and_ids(env):
    h, idx, e = env
    f = idx.create_field("f")
    g = idx.create_field("g")
    f.import_bits([0] * 3 + [1] * 2, [0, 1, 2, 1, 2])
    g.import_bits([9] * 2, [1, 2])
    (pairs,) = q(e, "TopN(f, Row(g=9))")
    assert pairs == [Pair(id=0, count=2), Pair(id=1, count=2)] or \
           pairs == [Pair(id=1, count=2), Pair(id=0, count=2)]
    (pairs,) = q(e, "TopN(f, ids=[1])")
    assert pairs == [Pair(id=1, count=2)]


def test_top_n_threshold_and_attr_filter(env):
    h, idx, e = env
    f = idx.create_field("f")
    f.import_bits([0] * 4 + [1] * 2 + [2], [0, 1, 2, 3, 0, 1, 5])
    (pairs,) = q(e, "TopN(f, threshold=2)")
    assert pairs == [Pair(id=0, count=4), Pair(id=1, count=2)]
    q(e, 'SetRowAttrs(f, 0, cat="x")')
    q(e, 'SetRowAttrs(f, 1, cat="y")')
    (pairs,) = q(e, 'TopN(f, attrName="cat", attrValues=["x"])')
    assert pairs == [Pair(id=0, count=4)]


def test_top_n_rejects_int_field(bsi_env):
    h, idx, e = bsi_env
    with pytest.raises(QueryError):
        q(e, "TopN(v)")


# -- Rows ------------------------------------------------------------------

def test_rows(env):
    h, idx, e = env
    f = idx.create_field("f")
    f.import_bits([1, 3, 5, 7], [1, 2, 3, SHARD_WIDTH + 4])
    assert q(e, "Rows(f)") == [RowIdentifiers(rows=[1, 3, 5, 7])]
    assert q(e, "Rows(f, previous=3)") == [RowIdentifiers(rows=[5, 7])]
    assert q(e, "Rows(f, limit=2)") == [RowIdentifiers(rows=[1, 3])]
    assert q(e, "Rows(f, column=2)") == [RowIdentifiers(rows=[3])]


# -- GroupBy ---------------------------------------------------------------

def test_group_by(env):
    h, idx, e = env
    a = idx.create_field("a")
    b = idx.create_field("b")
    # a row 0: cols {0,1,2}; a row 1: cols {1,2}
    a.import_bits([0, 0, 0, 1, 1], [0, 1, 2, 1, 2])
    # b row 0: cols {0,1}; b row 1: cols {2}
    b.import_bits([0, 0, 1], [0, 1, 2])
    (groups,) = q(e, "GroupBy(Rows(a), Rows(b))")
    got = {(tuple(fr.row_id for fr in g.group)): g.count for g in groups}
    assert got == {(0, 0): 2, (0, 1): 1, (1, 0): 1, (1, 1): 1}


def test_group_by_filter_and_limit(env):
    h, idx, e = env
    a = idx.create_field("a")
    b = idx.create_field("b")
    a.import_bits([0, 0, 1], [0, 1, 1])
    b.import_bits([0, 0], [0, 1])
    (groups,) = q(e, "GroupBy(Rows(a), Rows(b), filter=Row(a=0))")
    got = {(tuple(fr.row_id for fr in g.group)): g.count for g in groups}
    assert got == {(0, 0): 2, (1, 0): 1}
    (groups,) = q(e, "GroupBy(Rows(a), Rows(b), limit=1)")
    assert len(groups) == 1 and groups[0].count == 2


def test_group_by_previous(env):
    h, idx, e = env
    a = idx.create_field("a")
    b = idx.create_field("b")
    a.import_bits([0, 1], [0, 0])
    b.import_bits([0, 1], [0, 0])
    (groups,) = q(e, "GroupBy(Rows(a, previous=0), Rows(b, previous=0))")
    got = [tuple(fr.row_id for fr in g.group) for g in groups]
    assert got == [(0, 1), (1, 0), (1, 1)]


def test_group_by_rejects_non_rows_child(env):
    h, idx, e = env
    idx.create_field("a")
    with pytest.raises(QueryError):
        q(e, "GroupBy(Row(a=1))")


# -- ClearRow / Store ------------------------------------------------------

def test_clear_row(env):
    h, idx, e = env
    f = idx.create_field("f")
    f.import_bits([1, 1, 2], [1, SHARD_WIDTH + 1, 2])
    assert q(e, "ClearRow(f=1)") == [True]
    assert q(e, "Count(Row(f=1))") == [0]
    assert q(e, "Count(Row(f=2))") == [1]
    assert q(e, "ClearRow(f=1)") == [False]


def test_store(env):
    h, idx, e = env
    f = idx.create_field("f")
    f.import_bits([1, 1], [3, SHARD_WIDTH + 4])
    assert q(e, "Store(Row(f=1), f=9)") == [True]
    (r,) = q(e, "Row(f=9)")
    assert r.columns().tolist() == [3, SHARD_WIDTH + 4]


# -- attrs -----------------------------------------------------------------

def test_row_attrs_attached(env):
    h, idx, e = env
    idx.create_field("f")
    q(e, "Set(1, f=7)")
    q(e, 'SetRowAttrs(f, 7, color="blue", weight=3)')
    (row,) = q(e, "Row(f=7)")
    assert row.attrs == {"color": "blue", "weight": 3}
    # Options(excludeRowAttrs=true)
    (row,) = q(e, "Options(Row(f=7), excludeRowAttrs=true)")
    assert row.attrs == {}
    (row,) = q(e, "Options(Row(f=7), excludeColumns=true)")
    assert row.columns().tolist() == []


def test_set_column_attrs(env):
    h, idx, e = env
    idx.create_field("f")
    q(e, 'SetColumnAttrs(9, name="bob")')
    assert idx.column_attr_store.attrs(9) == {"name": "bob"}


def test_options_shards(env):
    h, idx, e = env
    f = idx.create_field("f")
    f.import_bits([1, 1, 1], [0, SHARD_WIDTH, 2 * SHARD_WIDTH])
    (r,) = q(e, "Options(Row(f=1), shards=[0, 2])")
    assert r.columns().tolist() == [0, 2 * SHARD_WIDTH]


# -- time ------------------------------------------------------------------

def test_row_time_range(env):
    h, idx, e = env
    idx.create_field("t", FieldOptions(type=FIELD_TYPE_TIME, time_quantum="YMDH"))
    q(e, "Set(1, t=1, 2018-01-01T00:00)")
    q(e, "Set(2, t=1, 2018-06-05T12:00)")
    q(e, "Set(3, t=1, 2019-02-03T04:00)")
    (r,) = q(e, "Range(t=1, from='2018-01-01T00:00', to='2019-01-01T00:00')")
    assert r.columns().tolist() == [1, 2]
    (r,) = q(e, "Row(t=1, from='2018-06-01T00:00', to='2019-03-01T00:00')")
    assert r.columns().tolist() == [2, 3]
    # plain Row uses the standard view
    (r,) = q(e, "Row(t=1)")
    assert r.columns().tolist() == [1, 2, 3]


# -- mutex / bool ----------------------------------------------------------

def test_mutex_field_via_executor(env):
    h, idx, e = env
    idx.create_field("m", FieldOptions(type=FIELD_TYPE_MUTEX))
    q(e, "Set(5, m=1)")
    q(e, "Set(5, m=2)")
    assert q(e, "Count(Row(m=1))") == [0]
    assert q(e, "Count(Row(m=2))") == [1]


def test_bool_field_via_executor(env):
    h, idx, e = env
    idx.create_field("b", FieldOptions(type=FIELD_TYPE_BOOL))
    q(e, "Set(5, b=true)")
    (r,) = q(e, "Row(b=true)")
    assert r.columns().tolist() == [5]
    q(e, "Set(5, b=false)")
    (r,) = q(e, "Row(b=false)")
    assert r.columns().tolist() == [5]
    (r,) = q(e, "Row(b=true)")
    assert r.columns().tolist() == []


# -- keys ------------------------------------------------------------------

def test_index_and_field_keys(env):
    h, _, e = env
    idx = h.create_index("ki", IndexOptions(keys=True))
    idx.create_field("f", FieldOptions(keys=True))
    e.execute("ki", 'Set("alpha", f="red")')
    e.execute("ki", 'Set("beta", f="red")')
    (row,) = e.execute("ki", 'Row(f="red")')
    assert sorted(row.keys) == ["alpha", "beta"]
    (rows,) = e.execute("ki", "Rows(f)")
    assert rows.keys == ["red"] and rows.rows == []


# -- errors ----------------------------------------------------------------

def test_field_not_found(env):
    h, idx, e = env
    with pytest.raises(FieldNotFoundError):
        q(e, "Row(nope=1)")


def test_count_requires_single_child(env):
    h, idx, e = env
    idx.create_field("f")
    with pytest.raises(QueryError):
        q(e, "Count(Row(f=1), Row(f=2))")


def test_store_requires_set_field(bsi_env):
    h, idx, e = bsi_env
    with pytest.raises(QueryError):
        q(e, "Store(Row(v > 0), v=1)")


def test_parse_cache_not_poisoned_by_translation(env):
    """Call.clone() must deep-clone Call-valued args: translation
    rewrites the filter Row's key in place, and a shallow clone would
    bake index A's translated id into the parse-cached tree, corrupting
    the same query text run against index B (ADVICE r3 #1)."""
    h, _, e = env
    for name in ("a", "b"):
        idx = h.create_index(name)
        idx.create_field("f")
        idx.create_field("g", FieldOptions(keys=True))
    # "k" translates to different ids on a and b: allocate a decoy first
    # on b so the shared key lands on a different row id.
    e.execute("a", 'Set(1, g="k")')
    e.execute("b", 'Set(9, g="decoy")')
    e.execute("b", 'Set(2, g="k")')
    e.execute("a", "Set(1, f=0)")
    e.execute("b", "Set(2, f=0)")
    query = 'GroupBy(Rows(f), filter=Row(g="k"))'
    (ga,) = e.execute("a", query)
    (gb,) = e.execute("b", query)  # same text: parse cache hit
    assert [g.count for g in ga] == [1]
    assert [g.count for g in gb] == [1]


def test_call_clone_deep_copies_nested_calls():
    from pilosa_tpu.pql.parser import parse
    q = parse('GroupBy(Rows(f), filter=Row(g="k"))')
    call = q.calls[0]
    c2 = call.clone()
    c2.args["filter"].args["g"] = 42
    assert call.args["filter"].args["g"] == "k"
