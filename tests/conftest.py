"""Test harness config.

Runs the whole suite on the JAX CPU backend with 8 virtual devices — the
in-process analog of the reference's ``test.MustRunCluster(t, 3)``
(test/pilosa.go:343): multi-device semantics without TPU hardware.
Must run before any jax import.
"""

import os

# Force (not setdefault: the machine env pins JAX_PLATFORMS to the real
# TPU tunnel, and a sitecustomize re-asserts it) the CPU backend with 8
# virtual devices for all tests.
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture
def rng():
    return np.random.default_rng(42)


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: multi-process fault tests (tens of seconds)")
