"""Test harness config.

Runs the whole suite on the JAX CPU backend with 8 virtual devices — the
in-process analog of the reference's ``test.MustRunCluster(t, 3)``
(test/pilosa.go:343): multi-device semantics without TPU hardware.
Must run before any jax import.
"""

import os

# Force (not setdefault: the machine env pins JAX_PLATFORMS to the real
# TPU tunnel, and a sitecustomize re-asserts it) the CPU backend with 8
# virtual devices for all tests.
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pytest  # noqa: E402

# Opt-in runtime lock-order witness (analysis/witness.py): installed
# HERE — after jax (its internal locks are not ours to audit) and
# before any pilosa_tpu module is imported by test collection — so
# every lock the product creates during the suite is witnessed. CI
# wires PILOSA_TPU_WITNESS=1 into the overload/chaos jobs.
_witness = None
if os.environ.get("PILOSA_TPU_WITNESS") == "1":
    from pilosa_tpu.analysis import witness as _witness_mod  # noqa: E402

    _witness = _witness_mod.install()


@pytest.fixture(scope="session", autouse=True)
def _lock_order_witness():
    """Fail the session if the suite ever acquired two lock sites in
    both orders — a latent deadlock even when this run got lucky."""
    yield
    if _witness is not None:
        _witness.check()


@pytest.fixture
def rng():
    return np.random.default_rng(42)


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: multi-process fault tests (tens of seconds)")
