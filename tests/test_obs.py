"""Observability tests: stats counting, prometheus exposition, tracing
spans, logger, /metrics endpoint."""

import io
import urllib.request

from pilosa_tpu.core import Holder
from pilosa_tpu.exec import Executor
from pilosa_tpu.obs import (
    MemoryStats,
    NopStats,
    SimpleTracer,
    StandardLogger,
    prometheus_text,
    set_tracer,
    start_span,
)
from pilosa_tpu.obs.tracing import NopTracer


def test_memory_stats_tags():
    s = MemoryStats()
    s.count("Query")
    s.with_tags("index:i").count("Query", 2)
    s.gauge("goroutines", 5)
    s.timing("exec", 0.5)
    assert s.counter_value("Query") == 1
    assert s.counter_value("Query", "index:i") == 2
    text = prometheus_text(s)
    assert 'pilosa_Query{index="i"} 2' in text
    assert "pilosa_goroutines 5" in text
    assert "pilosa_exec_seconds_count 1" in text


def test_executor_counts_calls():
    h = Holder()
    idx = h.create_index("i")
    idx.create_field("f")
    stats = MemoryStats()
    e = Executor(h, stats=stats)
    e.execute("i", "Set(1, f=1)")
    e.execute("i", "Count(Row(f=1))")
    assert stats.counter_value("Set", "index:i") == 1
    assert stats.counter_value("Count", "index:i") == 1
    # Count's child Row is not double-counted as a top-level call
    assert stats.counter_value("Row", "index:i") == 0


def test_simple_tracer_records_spans():
    t = SimpleTracer()
    set_tracer(t)
    try:
        h = Holder()
        idx = h.create_index("i")
        idx.create_field("f")
        e = Executor(h)
        e.execute("i", "Set(1, f=1)")
        ops = [s.operation for s in t.spans]
        assert "Executor.executeSet" in ops
        assert all(s.duration is not None for s in t.spans)
    finally:
        set_tracer(NopTracer())


def test_start_span_contextmanager():
    t = SimpleTracer()
    set_tracer(t)
    try:
        with start_span("custom.op") as span:
            span.set_tag("k", "v")
        assert t.spans[0].operation == "custom.op"
        assert t.spans[0].tags == {"k": "v"}
    finally:
        set_tracer(NopTracer())


def test_logger_verbose_gate():
    buf = io.StringIO()
    log = StandardLogger(stream=buf, verbose=False)
    log.printf("hello %s", "world")
    log.debugf("hidden")
    out = buf.getvalue()
    assert "hello world" in out and "hidden" not in out
    log2 = StandardLogger(stream=buf, verbose=True)
    log2.debugf("shown")
    assert "shown" in buf.getvalue()


def test_metrics_endpoint():
    from pilosa_tpu.server.node import ServerNode
    n = ServerNode(bind="127.0.0.1:0", use_planner=False)
    n.open()
    try:
        base = n.address
        urllib.request.urlopen(urllib.request.Request(
            base + "/index/i", data=b"{}", method="POST"), timeout=10)
        urllib.request.urlopen(urllib.request.Request(
            base + "/index/i/field/f", data=b"{}", method="POST"), timeout=10)
        urllib.request.urlopen(urllib.request.Request(
            base + "/index/i/query", data=b"Set(1, f=1)", method="POST"),
            timeout=10)
        text = urllib.request.urlopen(base + "/metrics", timeout=10).read().decode()
        assert 'pilosa_Set{index="i"} 1' in text
    finally:
        n.close()
