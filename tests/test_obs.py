"""Observability tests: stats counting, prometheus exposition, tracing
spans, logger, /metrics endpoint."""

import io
import urllib.request

from pilosa_tpu.core import Holder
from pilosa_tpu.exec import Executor
from pilosa_tpu.obs import (
    MemoryStats,
    NopStats,
    SimpleTracer,
    StandardLogger,
    prometheus_text,
    set_tracer,
    start_span,
)
from pilosa_tpu.obs.tracing import NopTracer


def test_memory_stats_tags():
    s = MemoryStats()
    s.count("Query")
    s.with_tags("index:i").count("Query", 2)
    s.gauge("goroutines", 5)
    s.timing("exec", 0.5)
    assert s.counter_value("Query") == 1
    assert s.counter_value("Query", "index:i") == 2
    text = prometheus_text(s)
    assert 'pilosa_Query{index="i"} 2' in text
    assert "pilosa_goroutines 5" in text
    assert "pilosa_exec_seconds_count 1" in text


def test_executor_counts_calls():
    h = Holder()
    idx = h.create_index("i")
    idx.create_field("f")
    stats = MemoryStats()
    e = Executor(h, stats=stats)
    e.execute("i", "Set(1, f=1)")
    e.execute("i", "Count(Row(f=1))")
    assert stats.counter_value("Set", "index:i") == 1
    assert stats.counter_value("Count", "index:i") == 1
    # Count's child Row is not double-counted as a top-level call
    assert stats.counter_value("Row", "index:i") == 0


def test_simple_tracer_records_spans():
    t = SimpleTracer()
    set_tracer(t)
    try:
        h = Holder()
        idx = h.create_index("i")
        idx.create_field("f")
        e = Executor(h)
        e.execute("i", "Set(1, f=1)")
        ops = [s.operation for s in t.spans]
        assert "Executor.executeSet" in ops
        assert all(s.duration is not None for s in t.spans)
    finally:
        set_tracer(NopTracer())


def test_start_span_contextmanager():
    t = SimpleTracer()
    set_tracer(t)
    try:
        with start_span("custom.op") as span:
            span.set_tag("k", "v")
        assert t.spans[0].operation == "custom.op"
        assert t.spans[0].tags["k"] == "v"
        assert "trace.id" in t.spans[0].tags  # spans join a trace
    finally:
        set_tracer(NopTracer())


def test_logger_verbose_gate():
    buf = io.StringIO()
    log = StandardLogger(stream=buf, verbose=False)
    log.printf("hello %s", "world")
    log.debugf("hidden")
    out = buf.getvalue()
    assert "hello world" in out and "hidden" not in out
    log2 = StandardLogger(stream=buf, verbose=True)
    log2.debugf("shown")
    assert "shown" in buf.getvalue()


def test_metrics_endpoint():
    from pilosa_tpu.server.node import ServerNode
    n = ServerNode(bind="127.0.0.1:0", use_planner=False)
    n.open()
    try:
        base = n.address
        urllib.request.urlopen(urllib.request.Request(
            base + "/index/i", data=b"{}", method="POST"), timeout=10)
        urllib.request.urlopen(urllib.request.Request(
            base + "/index/i/field/f", data=b"{}", method="POST"), timeout=10)
        urllib.request.urlopen(urllib.request.Request(
            base + "/index/i/query", data=b"Set(1, f=1)", method="POST"),
            timeout=10)
        text = urllib.request.urlopen(base + "/metrics", timeout=10).read().decode()
        assert 'pilosa_Set{index="i"} 1' in text
    finally:
        n.close()


def test_statsd_wire_format():
    import socket
    rx = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    rx.bind(("127.0.0.1", 0))
    rx.settimeout(5)
    port = rx.getsockname()[1]
    from pilosa_tpu.obs import StatsdStats
    st = StatsdStats(host="127.0.0.1", port=port)
    st.count("queries", 3)
    st.gauge("heap", 12.5)
    st.with_tags("index:i").timing("exec", 0.25)
    got = sorted(rx.recv(512).decode() for _ in range(3))
    assert got[0] == "pilosa.exec:250.000|ms|#index:i"
    assert got[1] == "pilosa.heap:12.5|g"
    assert got[2] == "pilosa.queries:3|c"
    rx.close()


def test_runtime_gauges():
    from pilosa_tpu.core import Holder
    from pilosa_tpu.obs import MemoryStats, collect_runtime_gauges
    from pilosa_tpu.parallel import MeshPlanner, make_mesh
    h = Holder()
    idx = h.create_index("i")
    f = idx.create_field("f")
    f.import_bits([1] * 5, [0, 1, 2, 3, 4])
    planner = MeshPlanner(h, make_mesh())
    from pilosa_tpu.exec import Executor
    Executor(h, planner=planner).execute("i", "Count(Row(f=1))")
    stats = MemoryStats()
    out = collect_runtime_gauges(stats, planner)
    assert out["threads"] >= 1
    assert out.get("rssBytes", 1) > 0
    assert out["plannerCacheEntries"] >= 1
    assert out["plannerCacheBytes"] > 0
    assert stats.gauges[("runtime.plannerCacheBudgetBytes", ())] == \
        planner.max_cache_bytes
    from pilosa_tpu import native
    if native.available():
        # Import buffer-pool gauges ride the same sweep.
        assert "poolLimitBytes" in out
        assert out["poolLimitBytes"] > 0


def test_trace_propagates_across_nodes():
    """A remote sub-query's spans carry the coordinator's trace id
    (reference InjectHTTPHeaders/ExtractHTTPHeaders, tracing.go:37)."""
    import json
    import urllib.request
    from pilosa_tpu.obs import SimpleTracer, set_tracer, NopTracer
    from pilosa_tpu.server.node import ServerNode
    import socket

    ports = []
    for _ in range(2):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        ports.append(s.getsockname()[1])
        s.close()
    addrs = [f"127.0.0.1:{p}" for p in ports]
    tracer = SimpleTracer()
    set_tracer(tracer)
    nodes = [ServerNode(bind=a, peers=[x for x in addrs if x != a],
                        use_planner=False, anti_entropy_interval=0.0,
                        check_nodes_interval=0.0) for a in addrs]
    for n in nodes:
        n.open()
    try:
        base = nodes[0].address

        def post(path, body=""):
            r = urllib.request.Request(base + path, data=body.encode(),
                                       method="POST")
            return json.loads(urllib.request.urlopen(r, timeout=10).read()
                              or b"{}")

        post("/index/t")
        post("/index/t/field/f")
        # Bits across enough shards that BOTH nodes own some.
        from pilosa_tpu.config import SHARD_WIDTH
        for s in range(16):
            post("/index/t/query", f"Set({s * SHARD_WIDTH}, f=1)")
        tracer.spans.clear()
        assert post("/index/t/query", "Count(Row(f=1))") == \
            {"results": [16]}
        exec_spans = [s for s in tracer.spans
                      if s.operation.startswith("Executor.execute")]
        ids = {s.tags.get("trace.id") for s in exec_spans}
        assert len(exec_spans) >= 2     # coordinator + remote node
        assert len(ids) == 1 and None not in ids
    finally:
        set_tracer(NopTracer())
        for n in nodes:
            try:
                n.close()
            except Exception:
                pass


def test_otlp_exporter_against_collector_double(tmp_path):
    """OTLPTracer (VERDICT r4 #9): spans flush as OTLP/HTTP JSON to a
    local collector double; structure and parentage survive."""
    import http.server
    import json
    import threading

    from pilosa_tpu.obs.otlp import OTLPTracer

    received = []

    class Collector(http.server.BaseHTTPRequestHandler):
        def do_POST(self):
            n = int(self.headers.get("Content-Length") or 0)
            received.append(json.loads(self.rfile.read(n)))
            self.send_response(200)
            self.send_header("Content-Length", "0")
            self.end_headers()

        def log_message(self, *a):
            pass

    srv = http.server.ThreadingHTTPServer(("127.0.0.1", 0), Collector)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    try:
        tr = OTLPTracer(
            endpoint=f"http://127.0.0.1:{srv.server_port}/v1/traces",
            service_name="test-node", flush_interval=60.0)
        parent = tr.start_span("Executor.Execute")
        parent.set_tag("index", "i")
        child = tr.start_span("planner.count", parent_id=parent.span_id)
        child.finish()
        parent.finish()
        tr.flush()
        assert tr.exported == 2 and tr.dropped == 0
        (batch,) = received
        rs = batch["resourceSpans"][0]
        svc = rs["resource"]["attributes"][0]
        assert svc["key"] == "service.name"
        assert svc["value"]["stringValue"] == "test-node"
        spans = rs["scopeSpans"][0]["spans"]
        by_name = {s["name"]: s for s in spans}
        assert set(by_name) == {"Executor.Execute", "planner.count"}
        p = by_name["Executor.Execute"]
        c = by_name["planner.count"]
        assert c["parentSpanId"] == p["spanId"]
        assert len(p["traceId"]) == 32 and len(p["spanId"]) == 16
        assert int(p["endTimeUnixNano"]) >= int(p["startTimeUnixNano"])
        assert {"key": "index", "value": {"stringValue": "i"}} \
            in p["attributes"]
        tr.close()
    finally:
        srv.shutdown()


def test_otlp_exporter_collector_down_never_raises():
    from pilosa_tpu.obs.otlp import OTLPTracer
    tr = OTLPTracer(endpoint="http://127.0.0.1:1/v1/traces",
                    flush_interval=60.0, timeout=0.5)
    tr.start_span("x").finish()
    tr.flush()  # collector unreachable: drop, don't raise
    assert tr.dropped == 1
    tr.close()


def test_debug_profile_route_returns_pstats_blob(tmp_path):
    """/debug/profile?seconds=N yields a non-empty blob the standard
    pstats tooling loads (VERDICT r4 #9 done-bar)."""
    import pstats
    import threading
    import time
    import urllib.request

    from pilosa_tpu.server.node import ServerNode

    n = ServerNode(bind="127.0.0.1:0", use_planner=False)
    n.open()
    stop = threading.Event()

    def busy():  # give the sampler something to see
        while not stop.is_set():
            sum(i * i for i in range(2000))
            time.sleep(0.001)

    t = threading.Thread(target=busy, daemon=True)
    t.start()
    try:
        with urllib.request.urlopen(
                n.address + "/debug/profile?seconds=0.4",
                timeout=30) as resp:
            blob = resp.read()
            assert resp.headers["Content-Type"] == \
                "application/octet-stream"
        assert len(blob) > 0
        path = tmp_path / "profile.pstats"
        path.write_bytes(blob)
        st = pstats.Stats(str(path))
        assert st.total_calls > 0
        funcs = {f for (_, _, f) in st.stats}
        assert "busy" in funcs  # the sampler saw the busy thread
    finally:
        stop.set()
        n.close()


def test_heap_stats_accounts_all_tiers():
    """obs.heap.heap_stats answers 'where did the RAM go' in one dict:
    host rows per index, native pool, planner HBM cache, tracemalloc
    (VERDICT r4 #5 done-bar)."""
    import numpy as np

    from pilosa_tpu.obs.heap import heap_stats
    from pilosa_tpu.parallel import MeshPlanner, make_mesh

    holder = Holder()
    idx = holder.create_index("hp")
    f = idx.create_field("f")
    rng = np.random.default_rng(7)
    f.import_bits(rng.integers(0, 3, 5000), rng.integers(0, 1 << 21, 5000))
    planner = MeshPlanner(holder, make_mesh(n=4))
    e = Executor(holder, planner=planner)
    e.execute("hp", "Count(Row(f=1))")  # populate the stack cache

    out = heap_stats(holder, planner=planner)
    hp = out["host_rows"]["hp"]
    assert hp["fragments"] >= 2 and hp["rows"] >= 3
    assert hp["host_row_bytes"] > 0
    assert out["planner_cache"]["bytes"] > 0
    assert out["planner_cache"]["budget_bytes"] > 0
    assert "native_pool" in out
    # First call arms tracemalloc; second sees sites.
    out2 = heap_stats(holder, planner=planner)
    assert out2["tracemalloc"]["tracing"] in ("on", "started")
    if out2["tracemalloc"]["tracing"] == "on":
        assert out2["tracemalloc"]["traced_current_bytes"] >= 0


def test_debug_heap_route():
    import json
    import urllib.request

    from pilosa_tpu.server.node import ServerNode

    n = ServerNode(bind="127.0.0.1:0", use_planner=False)
    n.open()
    try:
        urllib.request.urlopen(urllib.request.Request(
            n.address + "/index/hr", method="POST"), timeout=10)
        urllib.request.urlopen(urllib.request.Request(
            n.address + "/index/hr/field/f", method="POST"), timeout=10)
        urllib.request.urlopen(urllib.request.Request(
            n.address + "/index/hr/query", data=b"Set(1, f=1)",
            method="POST"), timeout=10)
        with urllib.request.urlopen(n.address + "/debug/heap?top=5",
                                    timeout=10) as resp:
            out = json.loads(resp.read())
        assert out["host_rows"]["hr"]["rows"] >= 1
        assert out["host_rows"]["hr"]["host_row_bytes"] >= 0
        assert "tracemalloc" in out and "native_pool" in out
        assert out.get("vmrss_kib", 1) > 0
    finally:
        n.close()


# -- ISSUE 11: SLO histograms, per-query profiles, device telemetry ------


def test_log_histogram_observe_merge_quantile():
    from pilosa_tpu.obs import LogHistogram, SECONDS_BOUNDS
    h = LogHistogram()
    for v in (0.0002, 0.0002, 0.01, 0.5):
        h.observe(v)
    assert h.count == 4 and abs(h.sum - 0.5104) < 1e-12
    assert 0.0001 <= h.quantile(0.5) <= 0.01
    items = h.bucket_items()
    assert items[-1] == ("+Inf", 4)
    cums = [c for _, c in items]
    assert cums == sorted(cums)          # cumulative by construction
    other = LogHistogram()
    other.observe(100.0)                 # overflows into +Inf
    h.merge(other)
    assert h.count == 5 and h.bucket_items()[-1] == ("+Inf", 5)
    # a +Inf rank floors to the last finite bound (documented behavior)
    assert other.quantile(0.99) == SECONDS_BOUNDS[-1]
    # memory stays O(buckets) no matter how many observations land
    for _ in range(10_000):
        h.observe(0.001)
    assert len(h.counts) == len(h.bounds) + 1
    snap = h.snapshot()
    assert snap["count"] == h.count and snap["p99"] > 0


def test_log_histogram_exemplars():
    from pilosa_tpu.obs import LogHistogram
    h = LogHistogram()
    for _ in range(200):
        h.observe(0.0002)
    h.observe(5.0, trace_id="t-slow")
    slow_i = next(j for j in range(len(h.counts))
                  if h.exemplar(j) is not None)
    # the slow observation's bucket sits at/above the p99 bucket and
    # keeps its trace id
    assert slow_i >= h.p99_bucket_index()
    assert h.exemplar(slow_i) == (5.0, "t-slow")


def test_memory_stats_timings_bounded():
    """Satellite: the unbounded per-series timing lists are gone —
    10k observations cost O(buckets), and the accessors still work."""
    from pilosa_tpu.obs import LogHistogram
    s = MemoryStats()
    for _ in range(10_000):
        s.timing("exec", 0.001)
    h = s.timings[("exec", ())]
    assert isinstance(h, LogHistogram)
    assert len(h.counts) == len(h.bounds) + 1
    assert s.timing_count("exec") == 10_000
    assert abs(s.timing_sum("exec") - 10.0) < 1e-6
    assert 0.0005 < s.timing_quantile("exec", 0.5) < 0.005


def test_prometheus_histogram_scrape_reparse():
    """Satellite: real `histogram` exposition — scrape the payload and
    re-parse the bucket series, _count/_sum, and the p99 exemplar."""
    import re
    from pilosa_tpu.obs import tracing as tr
    s = MemoryStats()
    for _ in range(200):
        s.timing("exec", 0.0002)
    tok = tr.set_current_trace("trace-slow-1")
    try:
        s.timing("exec", 2.0)     # slow observation carries the trace
    finally:
        tr.reset_current_trace(tok)
    text = prometheus_text(s)
    assert "# TYPE pilosa_exec_seconds histogram" in text
    bucket_re = re.compile(
        r'^pilosa_exec_seconds_bucket\{le="([^"]+)"\} (\d+)'
        r'(?: # \{trace_id="([^"]+)"\} ([0-9.eE+-]+))?$')
    buckets, exemplars = [], {}
    count = total_sum = None
    for line in text.splitlines():
        m = bucket_re.match(line)
        if m:
            buckets.append((m.group(1), int(m.group(2))))
            if m.group(3):
                exemplars[m.group(1)] = (m.group(3), float(m.group(4)))
        elif line.startswith("pilosa_exec_seconds_count "):
            count = int(line.split()[-1])
        elif line.startswith("pilosa_exec_seconds_sum "):
            total_sum = float(line.split()[-1])
    assert buckets and buckets[-1][0] == "+Inf"
    cums = [c for _, c in buckets]
    assert cums == sorted(cums)              # cumulative and monotone
    assert count == 201 and buckets[-1][1] == count
    assert total_sum is not None
    assert abs(total_sum - (200 * 0.0002 + 2.0)) < 1e-9
    # the slow tail carries the exemplar, linked by trace id; the fast
    # (p50) bucket stays exemplar-free
    assert any(tid == "trace-slow-1" for tid, _ in exemplars.values())
    assert "0.0002" not in exemplars


def _free_ports(n):
    import socket
    ports = []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        ports.append(s.getsockname()[1])
        s.close()
    return ports


def test_cluster_profile_accounts_every_leg():
    """Acceptance: ?profile=true on a 3-node cluster returns a timeline
    whose per-peer wire bytes and decode ms sum to the coordinator's
    totals, every remote leg accounted exactly once, each carrying the
    peer's own nested ledger home in the frames header."""
    import json
    from pilosa_tpu.config import SHARD_WIDTH
    from pilosa_tpu.server.node import ServerNode

    addrs = [f"127.0.0.1:{p}" for p in _free_ports(3)]
    nodes = [ServerNode(bind=a, peers=addrs, use_planner=False,
                        anti_entropy_interval=0.0,
                        check_nodes_interval=0.0,
                        qos_slow_query_ms=0.0) for a in addrs]
    for n in nodes:
        n.open()
    try:
        base = nodes[0].address

        def post(path, body=""):
            r = urllib.request.Request(base + path, data=body.encode(),
                                       method="POST")
            return json.loads(urllib.request.urlopen(r, timeout=10).read()
                              or b"{}")

        post("/index/p", "{}")
        post("/index/p/field/f", "{}")
        for s in range(8):
            post("/index/p/query", f"Set({s * SHARD_WIDTH}, f=1)")
        resp = post("/index/p/query?profile=true", "Count(Row(f=1))")
        assert resp["results"] == [8]
        prof = resp["profile"]
        legs = prof["remoteLegs"]
        tot = prof["remoteTotals"]
        # every remote peer appears EXACTLY once (no hedging configured)
        leg_nodes = [leg["node"] for leg in legs]
        assert len(leg_nodes) == len(set(leg_nodes))
        assert set(leg_nodes) <= {n.id for n in nodes[1:]}
        assert not any(leg["hedged"] for leg in legs)
        # the acceptance invariant: totals are the sums of the legs
        assert tot["legs"] == len(legs) >= 1
        assert tot["bytesOut"] == sum(leg["bytesOut"] for leg in legs)
        assert tot["bytesIn"] == sum(leg["bytesIn"] for leg in legs)
        assert abs(tot["decodeMs"]
                   - sum(leg["decodeMs"] for leg in legs)) < 0.01
        assert tot["hedgedLegs"] == 0 and tot["errorLegs"] == 0
        # each leg's nested remote ledger joined the coordinator's trace
        for leg in legs:
            rp = leg["remote"]
            assert rp["traceId"] == prof["traceId"]
            assert rp["node"] == leg["node"]
        # retention: addressable by trace id and listed slowest-first
        tid = prof["traceId"]
        got = json.loads(urllib.request.urlopen(
            base + f"/debug/queries/{tid}", timeout=10).read())
        assert got["remoteTotals"] == tot
        listing = json.loads(urllib.request.urlopen(
            base + "/debug/queries", timeout=10).read())
        assert any(d["traceId"] == tid for d in listing["queries"])
        # satellite: the slow-query log entry links to the profile
        slow = json.loads(urllib.request.urlopen(
            base + "/debug/slow-queries", timeout=10).read())
        entry = next(e for e in slow["queries"]
                     if e.get("traceId") == tid)
        assert entry["profile"] == f"/debug/queries/{tid}"
    finally:
        for n in nodes:
            try:
                n.close()
            except Exception:
                pass


def test_profile_off_bit_identical_and_allocation_free():
    """Satellite: with profiling fully off the query path constructs no
    QueryProfile at all (the ctor is boobytrapped for the duration) and
    answers bit-identically to a profiling node."""
    import json
    from pilosa_tpu.obs import profile as _profile
    from pilosa_tpu.server.node import ServerNode

    def run(node, trap=False):
        base = node.address

        def post(path, body=""):
            r = urllib.request.Request(base + path, data=body.encode(),
                                       method="POST")
            return json.loads(urllib.request.urlopen(r, timeout=10).read()
                              or b"{}")

        post("/index/q", "{}")
        post("/index/q/field/f", "{}")
        for c in (1, 2, 3, 70):
            post("/index/q/query", f"Set({c}, f=1)")
        orig = _profile.QueryProfile.__init__
        if trap:
            def boom(self, *a, **k):
                raise AssertionError("QueryProfile built on the off path")
            _profile.QueryProfile.__init__ = boom
        try:
            return post("/index/q/query", "Row(f=1)")
        finally:
            _profile.QueryProfile.__init__ = orig

    n_off = ServerNode(bind="127.0.0.1:0", use_planner=False,
                       profile_ring_n=0, profile_queries=False)
    n_off.open()
    try:
        off = run(n_off, trap=True)
    finally:
        n_off.close()
    n_on = ServerNode(bind="127.0.0.1:0", use_planner=False)
    n_on.open()
    try:
        on = run(n_on)
    finally:
        n_on.close()
    assert off == on
    assert "profile" not in off


def test_debug_device_route_and_dispatch_profile():
    """/debug/device gathers residency bytes, upload counters, and the
    batch/wave width histograms in one view; a profiled query on a
    planner node ledgers its device dispatches."""
    import json
    from pilosa_tpu.server.node import ServerNode

    n = ServerNode(bind="127.0.0.1:0")   # planner ON
    n.open()
    try:
        base = n.address

        def post(path, body=""):
            r = urllib.request.Request(base + path, data=body.encode(),
                                       method="POST")
            return json.loads(urllib.request.urlopen(r, timeout=30).read()
                              or b"{}")

        post("/index/dv", "{}")
        post("/index/dv/field/f", "{}")
        for c in range(64):
            post("/index/dv/query", f"Set({c}, f=1)")
        resp = post("/index/dv/query?profile=true", "Count(Row(f=1))")
        assert resp["results"] == [64]
        prof = resp["profile"]
        assert prof["dispatch"]["count"] >= 1
        assert len(prof["dispatch"]["widths"]) >= 1
        out = json.loads(urllib.request.urlopen(
            base + "/debug/device", timeout=10).read())
        assert out["enabled"]
        assert out["uploads"] >= 1 and out["upload_bytes"] > 0
        assert out["batch_width_hist"]["count"] >= 1
        assert "queue_depth" in out
        assert "wave_width_hist" in out["transfer"]
    finally:
        n.close()
