"""Observability tests: stats counting, prometheus exposition, tracing
spans, logger, /metrics endpoint."""

import io
import urllib.request

from pilosa_tpu.core import Holder
from pilosa_tpu.exec import Executor
from pilosa_tpu.obs import (
    MemoryStats,
    NopStats,
    SimpleTracer,
    StandardLogger,
    prometheus_text,
    set_tracer,
    start_span,
)
from pilosa_tpu.obs.tracing import NopTracer


def test_memory_stats_tags():
    s = MemoryStats()
    s.count("Query")
    s.with_tags("index:i").count("Query", 2)
    s.gauge("goroutines", 5)
    s.timing("exec", 0.5)
    assert s.counter_value("Query") == 1
    assert s.counter_value("Query", "index:i") == 2
    text = prometheus_text(s)
    assert 'pilosa_Query{index="i"} 2' in text
    assert "pilosa_goroutines 5" in text
    assert "pilosa_exec_seconds_count 1" in text


def test_executor_counts_calls():
    h = Holder()
    idx = h.create_index("i")
    idx.create_field("f")
    stats = MemoryStats()
    e = Executor(h, stats=stats)
    e.execute("i", "Set(1, f=1)")
    e.execute("i", "Count(Row(f=1))")
    assert stats.counter_value("Set", "index:i") == 1
    assert stats.counter_value("Count", "index:i") == 1
    # Count's child Row is not double-counted as a top-level call
    assert stats.counter_value("Row", "index:i") == 0


def test_simple_tracer_records_spans():
    t = SimpleTracer()
    set_tracer(t)
    try:
        h = Holder()
        idx = h.create_index("i")
        idx.create_field("f")
        e = Executor(h)
        e.execute("i", "Set(1, f=1)")
        ops = [s.operation for s in t.spans]
        assert "Executor.executeSet" in ops
        assert all(s.duration is not None for s in t.spans)
    finally:
        set_tracer(NopTracer())


def test_start_span_contextmanager():
    t = SimpleTracer()
    set_tracer(t)
    try:
        with start_span("custom.op") as span:
            span.set_tag("k", "v")
        assert t.spans[0].operation == "custom.op"
        assert t.spans[0].tags["k"] == "v"
        assert "trace.id" in t.spans[0].tags  # spans join a trace
    finally:
        set_tracer(NopTracer())


def test_logger_verbose_gate():
    buf = io.StringIO()
    log = StandardLogger(stream=buf, verbose=False)
    log.printf("hello %s", "world")
    log.debugf("hidden")
    out = buf.getvalue()
    assert "hello world" in out and "hidden" not in out
    log2 = StandardLogger(stream=buf, verbose=True)
    log2.debugf("shown")
    assert "shown" in buf.getvalue()


def test_metrics_endpoint():
    from pilosa_tpu.server.node import ServerNode
    n = ServerNode(bind="127.0.0.1:0", use_planner=False)
    n.open()
    try:
        base = n.address
        urllib.request.urlopen(urllib.request.Request(
            base + "/index/i", data=b"{}", method="POST"), timeout=10)
        urllib.request.urlopen(urllib.request.Request(
            base + "/index/i/field/f", data=b"{}", method="POST"), timeout=10)
        urllib.request.urlopen(urllib.request.Request(
            base + "/index/i/query", data=b"Set(1, f=1)", method="POST"),
            timeout=10)
        text = urllib.request.urlopen(base + "/metrics", timeout=10).read().decode()
        assert 'pilosa_Set{index="i"} 1' in text
    finally:
        n.close()


def test_statsd_wire_format():
    import socket
    rx = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    rx.bind(("127.0.0.1", 0))
    rx.settimeout(5)
    port = rx.getsockname()[1]
    from pilosa_tpu.obs import StatsdStats
    st = StatsdStats(host="127.0.0.1", port=port)
    st.count("queries", 3)
    st.gauge("heap", 12.5)
    st.with_tags("index:i").timing("exec", 0.25)
    got = sorted(rx.recv(512).decode() for _ in range(3))
    assert got[0] == "pilosa.exec:250.000|ms|#index:i"
    assert got[1] == "pilosa.heap:12.5|g"
    assert got[2] == "pilosa.queries:3|c"
    rx.close()


def test_runtime_gauges():
    from pilosa_tpu.core import Holder
    from pilosa_tpu.obs import MemoryStats, collect_runtime_gauges
    from pilosa_tpu.parallel import MeshPlanner, make_mesh
    h = Holder()
    idx = h.create_index("i")
    f = idx.create_field("f")
    f.import_bits([1] * 5, [0, 1, 2, 3, 4])
    planner = MeshPlanner(h, make_mesh())
    from pilosa_tpu.exec import Executor
    Executor(h, planner=planner).execute("i", "Count(Row(f=1))")
    stats = MemoryStats()
    out = collect_runtime_gauges(stats, planner)
    assert out["threads"] >= 1
    assert out.get("rssBytes", 1) > 0
    assert out["plannerCacheEntries"] >= 1
    assert out["plannerCacheBytes"] > 0
    assert stats.gauges[("runtime.plannerCacheBudgetBytes", ())] == \
        planner.max_cache_bytes
    from pilosa_tpu import native
    if native.available():
        # Import buffer-pool gauges ride the same sweep.
        assert "poolLimitBytes" in out
        assert out["poolLimitBytes"] > 0


def test_trace_propagates_across_nodes():
    """A remote sub-query's spans carry the coordinator's trace id
    (reference InjectHTTPHeaders/ExtractHTTPHeaders, tracing.go:37)."""
    import json
    import urllib.request
    from pilosa_tpu.obs import SimpleTracer, set_tracer, NopTracer
    from pilosa_tpu.server.node import ServerNode
    import socket

    ports = []
    for _ in range(2):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        ports.append(s.getsockname()[1])
        s.close()
    addrs = [f"127.0.0.1:{p}" for p in ports]
    tracer = SimpleTracer()
    set_tracer(tracer)
    nodes = [ServerNode(bind=a, peers=[x for x in addrs if x != a],
                        use_planner=False, anti_entropy_interval=0.0,
                        check_nodes_interval=0.0) for a in addrs]
    for n in nodes:
        n.open()
    try:
        base = nodes[0].address

        def post(path, body=""):
            r = urllib.request.Request(base + path, data=body.encode(),
                                       method="POST")
            return json.loads(urllib.request.urlopen(r, timeout=10).read()
                              or b"{}")

        post("/index/t")
        post("/index/t/field/f")
        # Bits across enough shards that BOTH nodes own some.
        from pilosa_tpu.config import SHARD_WIDTH
        for s in range(16):
            post("/index/t/query", f"Set({s * SHARD_WIDTH}, f=1)")
        tracer.spans.clear()
        assert post("/index/t/query", "Count(Row(f=1))") == \
            {"results": [16]}
        exec_spans = [s for s in tracer.spans
                      if s.operation.startswith("Executor.execute")]
        ids = {s.tags.get("trace.id") for s in exec_spans}
        assert len(exec_spans) >= 2     # coordinator + remote node
        assert len(ids) == 1 and None not in ids
    finally:
        set_tracer(NopTracer())
        for n in nodes:
            try:
                n.close()
            except Exception:
                pass


def test_otlp_exporter_against_collector_double(tmp_path):
    """OTLPTracer (VERDICT r4 #9): spans flush as OTLP/HTTP JSON to a
    local collector double; structure and parentage survive."""
    import http.server
    import json
    import threading

    from pilosa_tpu.obs.otlp import OTLPTracer

    received = []

    class Collector(http.server.BaseHTTPRequestHandler):
        def do_POST(self):
            n = int(self.headers.get("Content-Length") or 0)
            received.append(json.loads(self.rfile.read(n)))
            self.send_response(200)
            self.send_header("Content-Length", "0")
            self.end_headers()

        def log_message(self, *a):
            pass

    srv = http.server.ThreadingHTTPServer(("127.0.0.1", 0), Collector)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    try:
        tr = OTLPTracer(
            endpoint=f"http://127.0.0.1:{srv.server_port}/v1/traces",
            service_name="test-node", flush_interval=60.0)
        parent = tr.start_span("Executor.Execute")
        parent.set_tag("index", "i")
        child = tr.start_span("planner.count", parent_id=parent.span_id)
        child.finish()
        parent.finish()
        tr.flush()
        assert tr.exported == 2 and tr.dropped == 0
        (batch,) = received
        rs = batch["resourceSpans"][0]
        svc = rs["resource"]["attributes"][0]
        assert svc["key"] == "service.name"
        assert svc["value"]["stringValue"] == "test-node"
        spans = rs["scopeSpans"][0]["spans"]
        by_name = {s["name"]: s for s in spans}
        assert set(by_name) == {"Executor.Execute", "planner.count"}
        p = by_name["Executor.Execute"]
        c = by_name["planner.count"]
        assert c["parentSpanId"] == p["spanId"]
        assert len(p["traceId"]) == 32 and len(p["spanId"]) == 16
        assert int(p["endTimeUnixNano"]) >= int(p["startTimeUnixNano"])
        assert {"key": "index", "value": {"stringValue": "i"}} \
            in p["attributes"]
        tr.close()
    finally:
        srv.shutdown()


def test_otlp_exporter_collector_down_never_raises():
    from pilosa_tpu.obs.otlp import OTLPTracer
    tr = OTLPTracer(endpoint="http://127.0.0.1:1/v1/traces",
                    flush_interval=60.0, timeout=0.5)
    tr.start_span("x").finish()
    tr.flush()  # collector unreachable: drop, don't raise
    assert tr.dropped == 1
    tr.close()


def test_debug_profile_route_returns_pstats_blob(tmp_path):
    """/debug/profile?seconds=N yields a non-empty blob the standard
    pstats tooling loads (VERDICT r4 #9 done-bar)."""
    import pstats
    import threading
    import time
    import urllib.request

    from pilosa_tpu.server.node import ServerNode

    n = ServerNode(bind="127.0.0.1:0", use_planner=False)
    n.open()
    stop = threading.Event()

    def busy():  # give the sampler something to see
        while not stop.is_set():
            sum(i * i for i in range(2000))
            time.sleep(0.001)

    t = threading.Thread(target=busy, daemon=True)
    t.start()
    try:
        with urllib.request.urlopen(
                n.address + "/debug/profile?seconds=0.4",
                timeout=30) as resp:
            blob = resp.read()
            assert resp.headers["Content-Type"] == \
                "application/octet-stream"
        assert len(blob) > 0
        path = tmp_path / "profile.pstats"
        path.write_bytes(blob)
        st = pstats.Stats(str(path))
        assert st.total_calls > 0
        funcs = {f for (_, _, f) in st.stats}
        assert "busy" in funcs  # the sampler saw the busy thread
    finally:
        stop.set()
        n.close()


def test_heap_stats_accounts_all_tiers():
    """obs.heap.heap_stats answers 'where did the RAM go' in one dict:
    host rows per index, native pool, planner HBM cache, tracemalloc
    (VERDICT r4 #5 done-bar)."""
    import numpy as np

    from pilosa_tpu.obs.heap import heap_stats
    from pilosa_tpu.parallel import MeshPlanner, make_mesh

    holder = Holder()
    idx = holder.create_index("hp")
    f = idx.create_field("f")
    rng = np.random.default_rng(7)
    f.import_bits(rng.integers(0, 3, 5000), rng.integers(0, 1 << 21, 5000))
    planner = MeshPlanner(holder, make_mesh(n=4))
    e = Executor(holder, planner=planner)
    e.execute("hp", "Count(Row(f=1))")  # populate the stack cache

    out = heap_stats(holder, planner=planner)
    hp = out["host_rows"]["hp"]
    assert hp["fragments"] >= 2 and hp["rows"] >= 3
    assert hp["host_row_bytes"] > 0
    assert out["planner_cache"]["bytes"] > 0
    assert out["planner_cache"]["budget_bytes"] > 0
    assert "native_pool" in out
    # First call arms tracemalloc; second sees sites.
    out2 = heap_stats(holder, planner=planner)
    assert out2["tracemalloc"]["tracing"] in ("on", "started")
    if out2["tracemalloc"]["tracing"] == "on":
        assert out2["tracemalloc"]["traced_current_bytes"] >= 0


def test_debug_heap_route():
    import json
    import urllib.request

    from pilosa_tpu.server.node import ServerNode

    n = ServerNode(bind="127.0.0.1:0", use_planner=False)
    n.open()
    try:
        urllib.request.urlopen(urllib.request.Request(
            n.address + "/index/hr", method="POST"), timeout=10)
        urllib.request.urlopen(urllib.request.Request(
            n.address + "/index/hr/field/f", method="POST"), timeout=10)
        urllib.request.urlopen(urllib.request.Request(
            n.address + "/index/hr/query", data=b"Set(1, f=1)",
            method="POST"), timeout=10)
        with urllib.request.urlopen(n.address + "/debug/heap?top=5",
                                    timeout=10) as resp:
            out = json.loads(resp.read())
        assert out["host_rows"]["hr"]["rows"] >= 1
        assert out["host_rows"]["hr"]["host_row_bytes"] >= 0
        assert "tracemalloc" in out and "native_pool" in out
        assert out.get("vmrss_kib", 1) > 0
    finally:
        n.close()
