"""Randomized chaos soak (VERDICT r4 #7): a 3-node cluster of REAL
server processes under a seeded random schedule of faults — SIGKILL,
SIGSTOP/SIGCONT, remove-node, node (re)join, brand-new node admission
(grow to 4), resize-abort — interleaved with concurrent writes, clears,
batch imports, and queries.  At the end the cluster must converge to
NORMAL and every live node must answer the full query surface exactly
as a host-side oracle predicts.

The reference's closest shape is the pumba scenario suite
(internal/clustertests/cluster_test.go:28-95: dockerized pause +
import + recovery).  True network-link drops need netns/iptables this
environment doesn't offer; SIGSTOP covers the unresponsive-peer class
and the asymmetric-partition case has its own targeted test
(test_cluster.test_asymmetric_partition_does_not_mark_node_down).

Mid-chaos operations tolerate errors (a write may hit a RESIZING gate
or a dead node — that is the point); every intended state change is
recorded, and the heal phase re-applies the intent idempotently before
the final exact assertions, so an ambiguous in-flight failure can never
turn into a flaky assert.  Seeded: the same seed replays the same
schedule.
"""

import json
import os
import random
import signal
import socket
import subprocess
import sys
import time
import urllib.error
import urllib.request

import pytest

CHAOS_SECONDS = 6.0
N_ROWS = 3
COL_SPACE = 3 * (1 << 20)  # 3 shards' worth of columns


def _free_ports(n):
    out = []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        out.append(s.getsockname()[1])
        s.close()
    return out


def _spawn(addr, peers, data_dir, join=None, log_path=None):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    # setdefault: a drill that exported its own knob before building
    # the Soak wins over these soak-tuned values.
    env.setdefault("PILOSA_TPU_ANTI_ENTROPY_INTERVAL", "1.0")
    env.setdefault("PILOSA_TPU_CHECK_NODES_INTERVAL", "0.5")
    # A join target killed+restarted mid-apply never ACKs and the
    # failure detector may never see it down; a short ACK deadline
    # fails the wedged job and frees the resize gate for the joiner's
    # next announce.
    env.setdefault("PILOSA_TPU_RESIZE_ACK_TIMEOUT", "15")
    # Fast scrub so disk corruption injected mid-soak is found and
    # repaired within the heal window.
    env.setdefault("PILOSA_TPU_SCRUB_INTERVAL", "1.0")
    # The slow-peer drills drive POST /internal/fault; the route is
    # only mounted when chaos faults are explicitly enabled.
    env.setdefault("PILOSA_TPU_CHAOS_FAULTS", "1")
    argv = [sys.executable, "-m", "pilosa_tpu.cli", "server",
            "--bind", addr, "--replica-n", "2", "--no-planner",
            "--data-dir", data_dir]
    if join:
        argv += ["--join", join]
    else:
        argv += ["--peers", ",".join(peers)]
    out = open(log_path, "ab") if log_path else subprocess.DEVNULL
    return subprocess.Popen(argv, env=env, stdout=out, stderr=out)


def _wait_up(addr, timeout=90):
    deadline = time.time() + timeout
    while time.time() < deadline:
        try:
            urllib.request.urlopen(f"http://{addr}/status", timeout=2)
            return
        except Exception:
            time.sleep(0.4)
    raise TimeoutError(f"{addr} never came up")


def _post(addr, path, body="", timeout=30):
    r = urllib.request.Request(f"http://{addr}{path}",
                               data=body.encode(), method="POST")
    return json.loads(
        urllib.request.urlopen(r, timeout=timeout).read() or b"{}")


def _status(addr):
    return json.loads(urllib.request.urlopen(
        f"http://{addr}/status", timeout=10).read())


class Soak:
    """One node-process zoo + the intended-state oracle."""

    def __init__(self, tmp_path, seed: int):
        self.rng = random.Random(seed)
        self.tmp = tmp_path
        # Slot 3 is reserved for act_add_node (grow under fire): a port
        # and log name exist from the start, but the process and data
        # dir only appear once the soak decides to admit a 4th member.
        self.ports = _free_ports(4)
        self.addrs = [f"127.0.0.1:{p}" for p in self.ports]
        self.procs = {}
        self.paused: set[int] = set()
        self.spawn_n = 0
        #: each node's CURRENT data dir (re-joins get fresh dirs).
        self.dirs = {i: str(tmp_path / f"n{i}") for i in range(3)}
        for i in range(3):
            self.procs[i] = _spawn(
                self.addrs[i],
                [a for j, a in enumerate(self.addrs[:3]) if j != i],
                self.dirs[i],
                log_path=str(tmp_path / f"n{i}.log"))
        for a in self.addrs[:3]:
            _wait_up(a)
        #: nodes currently under a slow-peer fault (best effort: a
        #: kill/restart clears the fault server-side on its own).
        self.slowed: set[int] = set()
        #: intended bit state: (row, col) -> bool (last write wins).
        self.intent: dict[tuple[int, int], bool] = {}
        #: bits whose last operation ERRORED client-side: the server may
        #: or may not have applied it (e.g. response lost after apply,
        #: partial batch before a gate refusal). The heal phase clears
        #: any of these not later settled with certainty, making the
        #: final state fully determined.
        self.uncertain: set[tuple[int, int]] = set()

    # -- fault actions (node0 is the stable coordinator; 1, 2 and the
    # -- grown slot 3 are all fair game once admitted) ------------------

    def victims(self):
        return [i for i in (1, 2, 3) if i in self.procs]

    def act_kill(self):
        alive = [i for i in self.victims() if i not in self.paused]
        if not alive:
            return
        i = self.rng.choice(alive)
        self.procs[i].kill()
        self.procs[i].wait(timeout=10)
        del self.procs[i]

    def _respawn_join(self, i):
        """Operator re-admission flow: fresh dir, explicit join."""
        self.spawn_n += 1
        d = str(self.tmp / f"n{i}-re{self.spawn_n}")
        self.dirs[i] = d
        self.procs[i] = _spawn(self.addrs[i], [], d, join=self.addrs[0],
                               log_path=str(self.tmp / f"n{i}.log"))

    def act_restart(self):
        # Slot 3 only counts as restartable once act_add_node admitted
        # it at least once (it has a data dir from that admission).
        deadn = [i for i in (1, 2, 3)
                 if i not in self.procs and i in self.dirs]
        if not deadn:
            return
        i = self.rng.choice(deadn)
        # Fresh dir + explicit join half the time (exercises the join
        # resize), same dir otherwise (exercises WAL reload).
        if self.rng.random() < 0.5:
            self._respawn_join(i)
        else:
            self.procs[i] = _spawn(
                self.addrs[i],
                [a for j, a in enumerate(self.addrs[:3]) if j != i],
                self.dirs[i],
                log_path=str(self.tmp / f"n{i}.log"))

    def act_add_node(self):
        """Grow under fire: admit a brand-new 4th member through the
        operator join flow while chaos is still running. Once admitted,
        slot 3 is a full citizen — kill/pause/remove/corrupt/slow all
        apply to it — and the heal phase settles the ring at four."""
        if 3 in self.procs or 3 in self.dirs:
            return
        self.dirs[3] = str(self.tmp / "n3")
        self.procs[3] = _spawn(self.addrs[3], [], self.dirs[3],
                               join=self.addrs[0],
                               log_path=str(self.tmp / "n3.log"))

    def act_pause(self):
        alive = [i for i in self.victims() if i not in self.paused]
        if not alive:
            return
        i = self.rng.choice(alive)
        os.kill(self.procs[i].pid, signal.SIGSTOP)
        self.paused.add(i)

    def act_resume(self):
        if not self.paused:
            return
        i = self.rng.choice(sorted(self.paused))
        os.kill(self.procs[i].pid, signal.SIGCONT)
        self.paused.discard(i)

    def act_remove_node(self):
        # Coordinator-driven membership removal of a live follower; the
        # victim enters terminal REMOVED — a later kill+join brings it
        # back (the operator flow).
        alive = [i for i in self.victims() if i not in self.paused]
        if not alive:
            return
        i = self.rng.choice(alive)
        try:
            _post(self.addrs[0], "/cluster/resize/remove-node",
                  json.dumps({"id": self.addrs[i]}), timeout=60)
            # Removed processes are parked; recycle into the dead pool
            # so act_restart can re-join them.
            self.procs[i].kill()
            self.procs[i].wait(timeout=10)
            del self.procs[i]
        except Exception:
            pass  # not NORMAL / mid-resize: legal refusal

    def act_resize_abort(self):
        try:
            _post(self.addrs[0], "/cluster/resize/abort", timeout=20)
        except Exception:
            pass  # no active job / gate: fine

    def act_slow_peer(self):
        """Gray failure: the victim keeps answering membership probes
        but serves every query late. The breaker/hedge layer — not the
        failure detector — has to route around it."""
        alive = [i for i in self.victims() if i not in self.paused]
        if not alive:
            return
        i = self.rng.choice(alive)
        ms = self.rng.randrange(50, 300)
        try:
            _post(self.addrs[i], "/internal/fault",
                  json.dumps({"slowMs": ms}), timeout=10)
            self.slowed.add(i)
        except Exception:
            pass  # victim died under us: fine

    def act_fast_peer(self):
        if not self.slowed:
            return
        i = self.rng.choice(sorted(self.slowed))
        try:
            _post(self.addrs[i], "/internal/fault",
                  json.dumps({"slowMs": 0}), timeout=10)
        except Exception:
            pass
        self.slowed.discard(i)

    def act_corrupt_snapshot(self):
        """Disk rot under a LIVE node: bit-flip one of its published
        snapshots. The scrubber's re-verification (1s interval) or the
        load-time check after a later restart must catch it; with
        replica_n=2 the final oracle assertions stay exact either way."""
        from pilosa_tpu.storage.faults import corrupt_file
        i = self.rng.choice(self.victims() or [0])
        snaps = []
        for root, _dirs, files in os.walk(self.dirs[i]):
            snaps += [os.path.join(root, f) for f in files
                      if f.endswith(".snap")]
        if not snaps:
            return
        try:
            corrupt_file(self.rng.choice(sorted(snaps)), "bitflip",
                         rng=self.rng)
        except OSError:
            pass  # racing the node's own snapshot publish: fine

    # -- workload actions ----------------------------------------------

    def act_write_batch(self):
        n = self.rng.randrange(5, 40)
        pairs = [(self.rng.randrange(N_ROWS),
                  self.rng.randrange(COL_SPACE)) for _ in range(n)]
        q = " ".join(f"Set({c}, f={r})" for r, c in pairs)
        try:
            _post(self.addrs[0], "/index/i/query", q, timeout=20)
            for r, c in pairs:
                self.intent[(r, c)] = True
                self.uncertain.discard((r, c))
        except Exception:
            self.uncertain.update((r, c) for r, c in pairs)

    def act_import_batch(self):
        n = self.rng.randrange(50, 300)
        rows = [self.rng.randrange(N_ROWS) for _ in range(n)]
        cols = [self.rng.randrange(COL_SPACE) for _ in range(n)]
        try:
            _post(self.addrs[0], "/index/i/field/f/import",
                  json.dumps({"rowIDs": rows, "columnIDs": cols}),
                  timeout=30)
            for r, c in zip(rows, cols):
                self.intent[(r, c)] = True
                self.uncertain.discard((r, c))
        except Exception:
            self.uncertain.update(zip(rows, cols))

    def act_clear(self):
        set_bits = [k for k, v in self.intent.items() if v]
        if not set_bits:
            return
        r, c = self.rng.choice(set_bits)
        try:
            _post(self.addrs[0], "/index/i/query", f"Clear({c}, f={r})",
                  timeout=20)
            self.intent[(r, c)] = False
            self.uncertain.discard((r, c))
        except Exception:
            self.uncertain.add((r, c))

    def act_query(self):
        targets = [self.addrs[0]] + [self.addrs[i] for i in self.victims()
                                     if i not in self.paused]
        a = self.rng.choice(targets)
        r = self.rng.randrange(N_ROWS)
        try:
            out = _post(a, "/index/i/query?noCache=true",
                        f"Count(Row(f={r}))", timeout=15)
            assert isinstance(out["results"][0], int)
        except (urllib.error.URLError, urllib.error.HTTPError, OSError,
                TimeoutError):
            pass  # mid-fault refusal/timeouts are legal; wrong SHAPE isn't

    # -- phases ---------------------------------------------------------

    ACTIONS = (  # (weight, name)
        (3, "act_write_batch"), (2, "act_import_batch"), (2, "act_clear"),
        (4, "act_query"), (1, "act_kill"), (2, "act_restart"),
        (1, "act_pause"), (2, "act_resume"), (1, "act_remove_node"),
        (1, "act_add_node"),
        (1, "act_resize_abort"), (1, "act_corrupt_snapshot"),
        (1, "act_slow_peer"), (1, "act_fast_peer"),
    )

    def run_chaos(self, seconds: float):
        names = [n for w, n in self.ACTIONS for _ in range(w)]
        deadline = time.time() + seconds
        while time.time() < deadline:
            getattr(self, self.rng.choice(names))()
            time.sleep(self.rng.uniform(0.02, 0.2))

    def heal(self):
        for i in sorted(self.paused):
            os.kill(self.procs[i].pid, signal.SIGCONT)
        self.paused.clear()
        # Clear slow-peer faults everywhere (a restarted process forgot
        # its fault already; posting 0 to a dead node is harmless).
        for i in list(self.procs):
            try:
                _post(self.addrs[i], "/internal/fault",
                      json.dumps({"slowMs": 0}), timeout=10)
            except Exception:
                pass
        self.slowed.clear()
        for _ in range(3):  # act_restart fills at most one slot per call
            self.act_restart()
        for i, _p in list(self.procs.items()):
            try:
                _wait_up(self.addrs[i])
            except TimeoutError:
                pass  # the settle loop below reaps and refills dead slots
        # Wait for the ring to settle: every node NORMAL and every ring
        # holding the expected member count — 3, or 4 once act_add_node
        # grew the cluster (slot 3 has a data dir iff it was admitted).
        # A node that restarted with its old data dir after a
        # membership removal correctly parks in terminal REMOVED —
        # recycle it through the operator flow (kill + fresh join).
        deadline = time.time() + 360
        last_abort = time.time()
        #: node -> when the coordinator's committed ring was first seen
        #: excluding it while the node itself still reported NORMAL.
        missing_since: dict[int, float] = {}
        while time.time() < deadline:
            # A process that exits DURING this wait (lost a startup race
            # with a mid-heal membership change) would otherwise park the
            # loop on connection-refused until the deadline: reap and
            # refill dead slots every iteration.
            for i, p in list(self.procs.items()):
                if p.poll() is not None:
                    del self.procs[i]
            self.act_restart()
            # Per-node status: one unreachable node must not blind the
            # sweep to a REMOVED peer that needs recycling.
            sts = {}
            for i in sorted(self.procs):
                try:
                    sts[i] = _status(self.addrs[i])
                except Exception:
                    pass
            # EVERY node must hold the full ring: a (re)joined node can
            # report NORMAL while still solo, and a solo member serves
            # neither schema nor writes.
            expected = 3 + (1 if 3 in self.dirs else 0)
            if (len(sts) == expected
                    and all(s["state"] == "NORMAL" for s in sts.values())
                    and all(len(s["nodes"]) == expected
                            for s in sts.values())):
                return
            for i, s in sts.items():
                if s["state"] == "REMOVED" and i != 0:
                    try:
                        self.procs[i].kill()
                        self.procs[i].wait(timeout=10)
                    except Exception:
                        pass
                    self._respawn_join(i)
            # Ambiguous removal: if a remove-node response is lost after
            # the server commits it, the un-killed victim keeps running
            # with its stale pre-removal ring and never learns it is no
            # longer a member — NORMAL forever, never REMOVED. Detect
            # "alive but excluded from the coordinator's committed ring"
            # (stable for 20s, so a join mid-announce is not shot down)
            # and recycle through the operator flow.
            if 0 in sts and sts[0]["state"] == "NORMAL":
                ring0 = {n["id"] for n in sts[0]["nodes"]}
                for i in list(self.procs):
                    s = sts.get(i)
                    if (i == 0 or s is None or s["state"] != "NORMAL"
                            or self.addrs[i] in ring0):
                        missing_since.pop(i, None)
                        continue
                    t0 = missing_since.setdefault(i, time.time())
                    if time.time() - t0 < 20:
                        continue
                    missing_since.pop(i, None)
                    try:
                        self.procs[i].kill()
                        self.procs[i].wait(timeout=10)
                    except Exception:
                        pass
                    self._respawn_join(i)
            # A resize job wedged on a participant that vanished
            # mid-stream holds the gate shut against every later join;
            # if nothing has settled for a while, kick it loose (an
            # aborted healthy join just re-announces).
            if time.time() - last_abort > 45:
                last_abort = time.time()
                try:
                    _post(self.addrs[0], "/cluster/resize/abort",
                          timeout=20)
                except Exception:
                    pass
            time.sleep(0.5)
        states = {}
        for i in sorted(self.procs):
            try:
                s = _status(self.addrs[i])
                states[i] = (s["state"], [n["id"] for n in s["nodes"]])
            except Exception as e:
                states[i] = repr(e)
        raise AssertionError(f"cluster never settled: {states}")

    def reapply_intent(self):
        """Idempotently enforce the intended final state (heals any
        mid-chaos write whose outcome was ambiguous)."""
        # Ambiguous bits with no later certain outcome get an explicit
        # Clear: the server may have applied the lost-response write.
        for pair in self.uncertain:
            self.intent.setdefault(pair, False)
        self.uncertain.clear()
        items = sorted(self.intent.items())
        for chunk_start in range(0, len(items), 200):
            chunk = items[chunk_start:chunk_start + 200]
            q = " ".join(
                (f"Set({c}, f={r})" if want else f"Clear({c}, f={r})")
                for (r, c), want in chunk)
            deadline = time.time() + 60
            while True:
                try:
                    _post(self.addrs[0], "/index/i/query", q, timeout=30)
                    break
                except Exception as e:
                    if time.time() > deadline:
                        body = ""
                        if isinstance(e, urllib.error.HTTPError):
                            body = e.read().decode(errors="replace")[:800]
                        states = {}
                        for i in sorted(self.procs):
                            try:
                                states[i] = _status(self.addrs[i])["state"]
                            except Exception as se:
                                states[i] = repr(se)
                        raise AssertionError(
                            f"reapply stuck: {e!r} body={body!r} "
                            f"states={states}") from e
                    time.sleep(0.5)

    def assert_converged(self):
        want = {r: sum(1 for (rr, _), v in self.intent.items()
                       if rr == r and v) for r in range(N_ROWS)}
        queries = {f"Count(Row(f={r}))": want[r] for r in range(N_ROWS)}
        # Cross-row algebra vs the oracle too.
        s0 = {c for (r, c), v in self.intent.items() if v and r == 0}
        s1 = {c for (r, c), v in self.intent.items() if v and r == 1}
        queries["Count(Intersect(Row(f=0), Row(f=1)))"] = len(s0 & s1)
        queries["Count(Union(Row(f=0), Row(f=1)))"] = len(s0 | s1)
        deadline = time.time() + 240
        last = None
        while time.time() < deadline:
            try:
                for i in sorted(self.procs):
                    for q, w in queries.items():
                        got = _post(self.addrs[i],
                                    "/index/i/query?noCache=true", q,
                                    timeout=20)["results"][0]
                        assert got == w, (self.addrs[i], q, got, w)
                return
            except Exception as e:
                # Repair may still be converging (count mismatch) or a
                # node may be briefly busy syncing (timeout/refusal);
                # only the deadline turns this into a failure.
                last = e
                time.sleep(1.0)
        raise AssertionError(f"never converged to oracle: {last!r}")

    def close(self):
        for _i, p in list(self.procs.items()):
            try:
                os.kill(p.pid, signal.SIGCONT)
            except Exception:
                pass
            try:
                p.kill()
                p.wait(timeout=10)
            except Exception:
                pass


@pytest.mark.slow
@pytest.mark.parametrize("seed", [101, 202, 303, 404, 505])
def test_chaos_soak(tmp_path, seed):
    soak = Soak(tmp_path, seed)
    try:
        _post(soak.addrs[0], "/index/i")
        _post(soak.addrs[0], "/index/i/field/f")
        soak.act_write_batch()
        soak.run_chaos(CHAOS_SECONDS)
        # On this 1-vCPU rig five consecutive soaks contend hard enough
        # that heal occasionally needs more runway than one deadline
        # window — retry the PRE-assert stages once. The convergence
        # retry deliberately does NOT re-apply intent: a write the
        # first reapply lost must stay lost and fail the assert, or an
        # intermittent lost-write bug (the class this test exists to
        # catch) could hide behind the retry.
        try:
            soak.heal()
            soak.reapply_intent()
        except AssertionError:
            soak.heal()
            soak.reapply_intent()
        try:
            soak.assert_converged()
        except AssertionError:
            soak.heal()  # contention: one more settle window, no rewrite
            soak.assert_converged()
    finally:
        soak.close()


@pytest.mark.slow
def test_corrupt_snapshot_recovery_across_restart(tmp_path):
    """Deterministic corruption drill on real server processes: flip a
    bit in a killed node's published snapshot, restart it on the same
    dir, and require exact convergence — the restarted node must detect
    the damage, serve via replicas, and let the scrubber repair it."""
    os.environ["PILOSA_TPU_MAX_OP_N"] = "20"  # snapshot early and often
    try:
        soak = Soak(tmp_path, 4242)
    finally:
        del os.environ["PILOSA_TPU_MAX_OP_N"]
    try:
        _post(soak.addrs[0], "/index/i")
        _post(soak.addrs[0], "/index/i/field/f")
        # Each Set is one WAL record: enough records on every shard to
        # cross max-op-n so snapshots are published (not just WALs).
        for shard in range(3):
            base_col = shard * (1 << 20)
            for batch in range(3):
                pairs = [(r, base_col + 100 * batch + 10 * i + r)
                         for r in range(N_ROWS) for i in range(10)]
                q = " ".join(f"Set({c}, f={r})" for r, c in pairs)
                _post(soak.addrs[0], "/index/i/query", q, timeout=60)
                for r, c in pairs:
                    soak.intent[(r, c)] = True

        def snaps_of(i):
            out = []
            for root, _dirs, files in os.walk(soak.dirs[i]):
                out += [os.path.join(root, fn) for fn in files
                        if fn.endswith(".snap")]
            return out

        deadline = time.time() + 60
        while time.time() < deadline and not snaps_of(1):
            time.sleep(0.3)
        assert snaps_of(1), "node1 never published a snapshot"

        soak.procs[1].kill()
        soak.procs[1].wait(timeout=10)
        del soak.procs[1]
        from pilosa_tpu.storage.faults import corrupt_file
        for snap in snaps_of(1):
            corrupt_file(snap, "bitflip", rng=soak.rng)
        soak.act_restart()  # only node1 is dead; may pick fresh-join too
        soak.heal()
        soak.assert_converged()
        # The evidence survives somewhere: either preserved *.quarantine
        # files (same-dir restart) or the abandoned dir (fresh re-join).
        if soak.dirs[1] == str(tmp_path / "n1"):
            qfiles = [os.path.join(root, fn)
                      for root, _d, files in os.walk(soak.dirs[1])
                      for fn in files if fn.endswith(".quarantine")]
            assert qfiles, "corrupt snapshot was not quarantined"
    finally:
        soak.close()


@pytest.mark.slow
def test_slow_peer_breaker_recovery(tmp_path):
    """Deterministic slow-peer drill on real server processes: node1
    keeps answering membership probes but serves every query 10s late,
    while entry queries carry a 2s default deadline. Hedged reads keep
    answers fast (zero client-visible failures), the abandoned slow
    legs open node1's circuit breaker at the coordinator, the failure
    detector does NOT evict the gray node, and after the heal a
    half-open probe re-closes the breaker."""
    knobs = {
        "PILOSA_TPU_BREAKER_THRESHOLD": "3",
        "PILOSA_TPU_BREAKER_COOLDOWN": "2",
        "PILOSA_TPU_HEDGE_DELAY_MS": "100",
        "PILOSA_TPU_QOS_DEFAULT_DEADLINE": "2.0",
        # The soak default (1s) interleaves successful anti-entropy
        # calls to the sick peer between the slow query legs, resetting
        # the consecutive-failure streak before it can reach the
        # threshold — exactly what this drill must observe latching.
        "PILOSA_TPU_ANTI_ENTROPY_INTERVAL": "30",
    }
    os.environ.update(knobs)
    try:
        soak = Soak(tmp_path, 777)
    finally:
        for k in knobs:
            del os.environ[k]

    def overload(addr):
        return json.loads(urllib.request.urlopen(
            f"http://{addr}/debug/overload", timeout=10).read())

    def breaker_state(addr, peer):
        peers = (overload(addr).get("breakers") or {}).get("peers", {})
        return peers.get(peer, {}).get("state", "closed")

    try:
        _post(soak.addrs[0], "/index/i")
        _post(soak.addrs[0], "/index/i/field/f")
        # bits on all three shards so every query fans out cluster-wide
        pairs = [(r, shard * (1 << 20) + 10 * i + r)
                 for shard in range(3) for r in range(N_ROWS)
                 for i in range(5)]
        q = " ".join(f"Set({c}, f={r})" for r, c in pairs)
        _post(soak.addrs[0], "/index/i/query", q, timeout=60)
        want = {r: sum(1 for rr, _ in pairs if rr == r)
                for r in range(N_ROWS)}

        _post(soak.addrs[1], "/internal/fault",
              json.dumps({"slowMs": 10000}))
        # Under the fault: every query must still succeed, and fast —
        # the hedge fires at 100ms and a replica answers.
        failures = 0
        for n in range(12):
            r = n % N_ROWS
            try:
                got = _post(soak.addrs[0], "/index/i/query?noCache=true",
                            f"Count(Row(f={r}))", timeout=30)["results"][0]
                assert got == want[r], (r, got, want[r])
            except (urllib.error.URLError, OSError, TimeoutError):
                failures += 1
        assert failures == 0, f"{failures} queries failed via slow peer"
        # The abandoned legs overran the 2s deadline: breaker opens.
        deadline = time.time() + 90
        state = breaker_state(soak.addrs[0], soak.addrs[1])
        while state != "open" and time.time() < deadline:
            _post(soak.addrs[0], "/index/i/query?noCache=true",
                  "Count(Row(f=0))", timeout=30)
            time.sleep(0.3)
            state = breaker_state(soak.addrs[0], soak.addrs[1])
        assert state == "open", f"breaker never opened (state={state})"
        # Gray failure: membership probes still pass, so node1 must
        # still be a full member of the coordinator's ring.
        st = _status(soak.addrs[0])
        assert st["state"] == "NORMAL"
        assert soak.addrs[1] in {n["id"] for n in st["nodes"]}

        # Heal; after the 2s cooldown one half-open probe re-closes it.
        _post(soak.addrs[1], "/internal/fault", json.dumps({"slowMs": 0}))
        deadline = time.time() + 90
        state = breaker_state(soak.addrs[0], soak.addrs[1])
        while state != "closed" and time.time() < deadline:
            _post(soak.addrs[0], "/index/i/query?noCache=true",
                  "Count(Row(f=0))", timeout=30)
            time.sleep(0.5)
            state = breaker_state(soak.addrs[0], soak.addrs[1])
        assert state == "closed", f"breaker never re-closed ({state})"
        # and the healed peer serves queries directly again
        got = _post(soak.addrs[1], "/index/i/query?noCache=true",
                    "Count(Row(f=0))", timeout=30)["results"][0]
        assert got == want[0]
    finally:
        soak.close()
