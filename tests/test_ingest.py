"""Streaming ingestion: the columnar import-stream wire (PTS1), the
device-side BSI bit-plane transpose, WAL group commit, and ingest/query
isolation.

Equivalence discipline (same contract as test_wire_fanout): every
optimized path — device transpose vs the host plane loop, the
vectorized value() gather vs the per-bit probe, binary timestamps vs
JSON — must be BIT-IDENTICAL to the path it replaces; the tests here
force each side and compare state, WAL bytes, and query results.
"""

import io
import json
import struct
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from pilosa_tpu.config import SHARD_WIDTH
from pilosa_tpu.obs.histogram import LogHistogram
from pilosa_tpu.cluster.harness import LocalCluster
from pilosa_tpu.cluster.node import URI, Node
from pilosa_tpu.core.fragment import Fragment
from pilosa_tpu.exec import ingest_transpose
from pilosa_tpu.qos import IngestBackpressureError, IngestGate
from pilosa_tpu.server import wire
from pilosa_tpu.server.api import API
from pilosa_tpu.server.httpclient import HTTPInternalClient, NodeHTTPError
from pilosa_tpu.server.httpd import _bounded_body_reader, _chunked_body_reader
from pilosa_tpu.server.node import ServerNode
from pilosa_tpu.storage.wal import WalReader, WalWriter


@pytest.fixture(autouse=True)
def _no_transpose_env(monkeypatch):
    """Each test picks its own mode explicitly; the env override and any
    leftover module mode must not leak between tests."""
    monkeypatch.delenv("PILOSA_TPU_INGEST_TRANSPOSE", raising=False)
    ingest_transpose.set_mode("auto")
    yield
    ingest_transpose.set_mode("auto")


def req(base, method, path, body=None, headers=None):
    data = body.encode() if isinstance(body, str) else body
    r = urllib.request.Request(base + path, data=data, method=method,
                               headers=headers or {})
    try:
        with urllib.request.urlopen(r, timeout=10) as resp:
            return resp.status, json.loads(resp.read() or b"{}"), resp.headers
    except urllib.error.HTTPError as e:
        payload = e.read()
        try:
            return e.code, json.loads(payload), e.headers
        except json.JSONDecodeError:
            return e.code, {"raw": payload.decode()}, e.headers


# -- stream wire format ------------------------------------------------------


def _stream_bytes(reqs):
    return b"".join([wire.stream_preamble()]
                    + [wire.stream_chunk(r) for r in reqs]
                    + [wire.stream_end()])


def test_stream_wire_roundtrip():
    reqs = [
        {"kind": "field", "index": "i", "field": "v", "shard": 0,
         "columnIDs": [1, 5, 9], "values": [-3, 0, 7], "clear": False},
        {"kind": "field", "index": "i", "field": "f", "shard": 1,
         "rowIDs": [2, 2, 4],
         "columnIDs": [SHARD_WIDTH + 1, SHARD_WIDTH + 2, SHARD_WIDTH + 3],
         "clear": False},
    ]
    buf = io.BytesIO(_stream_bytes(reqs))
    out = [wire.decode_import(f) for f in wire.iter_stream_frames(buf.read)]
    assert len(out) == 2
    assert out[0]["values"].tolist() == [-3, 0, 7]
    assert out[0]["columnIDs"].tolist() == [1, 5, 9]
    assert out[1]["rowIDs"].tolist() == [2, 2, 4]
    assert out[1]["index"] == "i" and out[1]["shard"] == 1


def test_stream_timestamps_sentinel_and_narrowing():
    """All-present epoch timestamps may narrow to u32 on the wire; a
    batch with Nones rides the u64 sentinel. Both decode back to the
    exact int/None list."""
    all_present = {"kind": "field", "index": "i", "field": "f", "shard": 0,
                   "rowIDs": [1, 1], "columnIDs": [3, 4],
                   "timestamps": [1700000000, 1700000001], "clear": False}
    mixed = {"kind": "field", "index": "i", "field": "f", "shard": 0,
             "rowIDs": [1, 1, 1], "columnIDs": [3, 4, 5],
             "timestamps": [1700000000, None, 1700000002], "clear": False}
    d1 = wire.decode_import(wire.encode_import(all_present))
    assert d1["timestamps"] == [1700000000, 1700000001]
    d2 = wire.decode_import(wire.encode_import(mixed))
    assert d2["timestamps"] == [1700000000, None, 1700000002]


def test_stream_truncated_and_oversized_raise():
    reqs = [{"kind": "field", "index": "i", "field": "v", "shard": 0,
             "columnIDs": [1], "values": [2], "clear": False}]
    good = _stream_bytes(reqs)
    torn = io.BytesIO(good[:-6])  # cut into the terminator + last frame
    with pytest.raises(ValueError):
        list(wire.iter_stream_frames(torn.read))
    huge = io.BytesIO(wire.stream_preamble()
                      + struct.pack("<I", wire.STREAM_MAX_CHUNK + 1))
    with pytest.raises(ValueError):
        list(wire.iter_stream_frames(huge.read))
    bad_magic = io.BytesIO(b"NOPE" + good[4:])
    with pytest.raises(ValueError):
        list(wire.iter_stream_frames(bad_magic.read))


# -- device transpose vs host plane loop -------------------------------------


def _frag_with_wal(mode, seed, prefill, batches, depth):
    """Build a fragment in the given transpose mode, applying prefill
    then each batch; returns (canonical row state, WAL records,
    sampled value() reads)."""
    ingest_transpose.set_mode(mode)
    f = Fragment("i", "v", "bsig_v", 0)
    records = []
    f.import_values(*prefill, depth)
    f.op_writer = lambda op, rows, cols: records.append(
        (op, np.asarray(rows, dtype=np.uint64).tobytes(),
         np.asarray(cols, dtype=np.uint64).tobytes()))
    for cols, vals, clear in batches:
        f.import_values(cols, vals, depth, clear=clear)
    state = {rid: hr.to_words().tobytes()
             for rid, hr in f.rows.items() if hr is not None and hr.n}
    f.VALUE_STACK_MIN = 0  # force the vectorized gather
    rng = np.random.default_rng(seed)
    probe_cols = rng.integers(0, 4096, 64).tolist()
    reads = [f.value(c, depth) for c in probe_cols]
    return state, records, reads


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_import_values_device_matches_host_generative(seed):
    """Force host and device transpose over identical generative
    workloads (duplicates, negatives, overwrites, clears) and require
    identical row state, identical WAL bytes, identical value() reads."""
    rng = np.random.default_rng(seed)
    depth = int(rng.integers(1, 40))
    lo, hi = -(1 << (depth - 1)) if depth > 1 else -1, (1 << (depth - 1))
    prefill = (rng.integers(0, 4096, 300), rng.integers(lo, hi, 300))
    batches = []
    for _ in range(4):
        n = int(rng.integers(1, 500))
        cols = rng.integers(0, 4096, n)
        vals = rng.integers(lo, hi, n)
        batches.append((cols, vals, bool(rng.integers(0, 5) == 0)))
    host = _frag_with_wal("off", seed, prefill, batches, depth)
    dev = _frag_with_wal("on", seed, prefill, batches, depth)
    assert host[0] == dev[0], "row state diverged"
    assert host[1] == dev[1], "WAL records diverged"
    assert host[2] == dev[2], "value() reads diverged"


def test_import_values_lww_duplicates_device():
    """Duplicate columns in one batch: last write wins, both modes."""
    for mode in ("off", "on"):
        ingest_transpose.set_mode(mode)
        f = Fragment("i", "v", "bsig_v", 0)
        f.import_values([7, 7, 7], [5, -9, 42], 8)
        assert f.value(7, 8) == (42, True), mode
        f.import_values([7, 3, 7], [1, 2, -6], 8)
        assert f.value(7, 8) == (-6, True), mode
        assert f.value(3, 8) == (2, True), mode


def test_import_values_clear_then_reimport_device():
    for mode in ("off", "on"):
        ingest_transpose.set_mode(mode)
        f = Fragment("i", "v", "bsig_v", 0)
        f.import_values([1, 2, 3], [10, -20, 30], 8)
        f.import_values([2], [], 8, clear=True)
        assert f.value(2, 8) == (0, False), mode
        assert f.value(1, 8) == (10, True), mode
        f.import_values([2], [-1], 8)
        assert f.value(2, 8) == (-1, True), mode


def test_import_values_shard_boundary_positions_device():
    """Columns at the very edges of a non-zero shard: the local-position
    mask and the device word indexing must agree at word 0 and the last
    word of the shard."""
    base = 3 * SHARD_WIDTH
    edges = [base, base + 1, base + 31, base + 32,
             base + SHARD_WIDTH - 33, base + SHARD_WIDTH - 1]
    vals = [1, -2, 3, -4, 5, -6]
    results = {}
    for mode in ("off", "on"):
        ingest_transpose.set_mode(mode)
        f = Fragment("i", "v", "bsig_v", 3)
        f.import_values(edges, vals, 8)
        results[mode] = [f.value(c, 8) for c in edges]
        assert results[mode] == [(v, True) for v in vals], mode
    assert results["off"] == results["on"]


def test_value_vectorized_matches_probe_loop():
    f = Fragment("i", "v", "bsig_v", 0)
    rng = np.random.default_rng(3)
    cols = rng.integers(0, 8192, 1000)
    vals = rng.integers(-500, 500, 1000)
    f.import_values(cols, vals, 16)
    probe_cols = list(range(0, 8192, 7))
    f.VALUE_STACK_MIN = 1 << 30  # force the per-bit probe loop
    probe = [f.value(c, 16) for c in probe_cols]
    f.VALUE_STACK_MIN = 0  # force the gather
    f._value_stack = None
    gather = [f.value(c, 16) for c in probe_cols]
    assert probe == gather


def test_ingest_transpose_mode_knob(monkeypatch):
    ingest_transpose.set_mode("on")
    assert ingest_transpose.use_device(1)
    ingest_transpose.set_mode("off")
    assert not ingest_transpose.use_device(1 << 30)
    monkeypatch.setenv("PILOSA_TPU_INGEST_TRANSPOSE", "on")
    assert ingest_transpose.use_device(1)  # env wins over set_mode
    with pytest.raises(ValueError):
        ingest_transpose.set_mode("sideways")


# -- WAL group commit --------------------------------------------------------


def test_wal_group_commit_coalesces_fsyncs(tmp_path):
    p = str(tmp_path / "f.wal")
    w = WalWriter(p, fsync_appends=True, group_window=0.02)
    n_threads, per_thread = 8, 5
    start = threading.Barrier(n_threads)

    def run(t):
        start.wait()
        for k in range(per_thread):
            w.append("add", [t], [t * 100 + k])

    threads = [threading.Thread(target=run, args=(t,))
               for t in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    total = n_threads * per_thread
    assert w.fsyncs < total, (w.fsyncs, total)
    assert w.fsyncs >= 1
    w.close()
    ops = list(WalReader(p))
    assert len(ops) == total
    seen = sorted((int(r[0]), int(c[0])) for _, r, c in ops)
    assert seen == sorted((t, t * 100 + k) for t in range(n_threads)
                          for k in range(per_thread))


def test_wal_group_commit_single_appender_durable(tmp_path):
    """A lone appender must not wait for company: its append returns
    after one windowed fsync and the record is on disk."""
    p = str(tmp_path / "f.wal")
    w = WalWriter(p, fsync_appends=True, group_window=0.01)
    t0 = time.perf_counter()
    w.append("add", [1], [2])
    assert time.perf_counter() - t0 < 5.0
    assert w.fsyncs == 1
    ops = list(WalReader(p))  # readable without close: fsync happened
    assert len(ops) == 1
    w.close()


def test_wal_group_commit_zero_window_is_per_append(tmp_path):
    p = str(tmp_path / "f.wal")
    w = WalWriter(p, fsync_appends=True)
    w.append("add", [1], [2])
    w.append("add", [3], [4])
    assert w.fsyncs == 2
    w.close()


# -- ingest gate (backpressure) ----------------------------------------------


def test_ingest_gate_budget_and_oversize():
    g = IngestGate(max_inflight_bytes=100)
    with g.admit(60):
        with pytest.raises(IngestBackpressureError) as ei:
            with g.admit(60):
                pass
        assert ei.value.retry_after >= 1.0
    # idle gate admits even an oversized chunk (degrades to serial)
    with g.admit(10_000):
        pass
    snap = g.snapshot()
    assert snap["rejected"] == 1 and snap["admitted"] == 2
    # disabled gate admits everything
    g0 = IngestGate(0)
    with g0.admit(1 << 40):
        pass


# -- HTTP body readers -------------------------------------------------------


def test_chunked_body_reader():
    raw = b"4\r\nWiki\r\n6\r\npedia \r\nB\r\nin chunks.\n\r\n0\r\n\r\n"
    read = _chunked_body_reader(io.BytesIO(raw))
    out = b""
    while True:
        b = read(5)
        if not b:
            break
        out += b
    assert out == b"Wiki" + b"pedia " + b"in chunks.\n"
    assert read(5) == b""  # stays at EOF


def test_bounded_body_reader():
    read = _bounded_body_reader(io.BytesIO(b"abcdefXXX"), 6)
    assert read(4) == b"abcd" and read(4) == b"ef" and read(4) == b""


# -- HTTP endpoint + client --------------------------------------------------


@pytest.fixture
def node():
    n = ServerNode(bind="127.0.0.1:0", use_planner=False)
    n.open()
    yield n
    n.close()


def _client_node(n):
    return Node(id=f"127.0.0.1:{n.port}",
                uri=URI(host="127.0.0.1", port=n.port))


def _value_req(shard, cols, vals, index="si"):
    return {"kind": "field", "index": index, "field": "v", "shard": shard,
            "rowIDs": None, "columnIDs": cols, "values": vals,
            "clear": False}


@pytest.mark.parametrize("chunked", [False, True])
def test_import_stream_http_end_to_end(node, chunked):
    b = node.address
    req(b, "POST", "/index/si", "{}")
    req(b, "POST", "/index/si/field/v",
        json.dumps({"options": {"type": "int", "min": -10_000,
                                "max": 10_000}}))
    client = HTTPInternalClient(timeout=10)
    try:
        reqs = [_value_req(s, [s * SHARD_WIDTH + c for c in range(10)],
                           [(s + 1) * 10 + c for c in range(10)])
                for s in range(4)]
        applied = client.send_import_stream(_client_node(node), reqs,
                                            chunked=chunked)
        assert applied == 4
        status, resp, _ = req(b, "POST", "/index/si/query",
                              "Sum(field=v)")
        want = sum((s + 1) * 10 + c for s in range(4) for c in range(10))
        assert resp["results"] == [{"value": want, "count": 40}]
    finally:
        client.close()


def test_import_stream_binary_timestamps_http(node):
    """send_import with per-element None timestamps rides the binary
    wire end-to-end (the old json_only escape hatch is gone)."""
    b = node.address
    req(b, "POST", "/index/ti", "{}")
    req(b, "POST", "/index/ti/field/t",
        json.dumps({"options": {"timeQuantum": "YMD"}}))
    client = HTTPInternalClient(timeout=10)
    try:
        client.send_import(_client_node(node), "ti", "t", 0,
                           rows=[1, 1, 1], cols=[3, 4, 5],
                           timestamps=[1700000000, None, 1700000000])
        status, resp, _ = req(b, "POST", "/index/ti/query", "Row(t=1)")
        assert resp["results"][0]["columns"] == [3, 4, 5]
        status, resp, _ = req(
            b, "POST", "/index/ti/query",
            "Row(t=1, from='2023-11-14T00:00', to='2023-11-16T00:00')")
        assert resp["results"][0]["columns"] == [3, 5]
    finally:
        client.close()


def test_import_stream_backpressure_http_429_applied(node):
    b = node.address
    req(b, "POST", "/index/bp", "{}")
    req(b, "POST", "/index/bp/field/v",
        json.dumps({"options": {"type": "int", "min": -100, "max": 100}}))
    chunk = {"kind": "field", "index": "bp", "field": "v", "shard": 0,
             "columnIDs": [1, 2], "values": [3, 4], "clear": False}
    node.ingest_gate.max_inflight_bytes = 64
    hold = node.ingest_gate.admit(32)
    hold.__enter__()
    try:
        status, resp, headers = req(
            b, "POST", "/internal/import-stream",
            _stream_bytes([chunk, chunk]),
            headers={"Content-Type": wire.STREAM_CONTENT_TYPE})
        assert status == 429, resp
        assert resp["applied"] == 0
        assert int(headers["Retry-After"]) >= 1
    finally:
        hold.__exit__(None, None, None)
    # gate released: the same stream now lands whole
    status, resp, _ = req(
        b, "POST", "/internal/import-stream", _stream_bytes([chunk]),
        headers={"Content-Type": wire.STREAM_CONTENT_TYPE})
    assert (status, resp) == (200, {"applied": 1})
    status, resp, _ = req(b, "POST", "/index/bp/query", "Sum(field=v)")
    assert resp["results"] == [{"value": 7, "count": 2}]


def test_import_stream_bad_chunk_reports_applied(node):
    """A chunk for a missing field: the server drains the rest, reports
    the error AND how far it got, and the connection stays usable."""
    b = node.address
    req(b, "POST", "/index/gx", "{}")
    req(b, "POST", "/index/gx/field/v",
        json.dumps({"options": {"type": "int", "min": -100, "max": 100}}))
    good = {"kind": "field", "index": "gx", "field": "v", "shard": 0,
            "columnIDs": [1], "values": [5], "clear": False}
    bad = {"kind": "field", "index": "gx", "field": "missing", "shard": 0,
           "columnIDs": [2], "values": [6], "clear": False}
    status, resp, _ = req(
        b, "POST", "/internal/import-stream",
        _stream_bytes([good, bad, good]),
        headers={"Content-Type": wire.STREAM_CONTENT_TYPE})
    assert status == 404, resp
    assert resp["applied"] == 1


def test_send_import_stream_resumes_from_applied(monkeypatch):
    """429 + {"applied": k} + Retry-After: the client sleeps, rebuilds
    the stream from chunk k, and finishes."""
    client = HTTPInternalClient()
    peer = Node(id="p1", uri=URI(host="127.0.0.1", port=1))
    reqs = [_value_req(s, [s], [s]) for s in range(3)]
    bodies = []
    replies = [(429, {"Retry-After": "0"},
                json.dumps({"applied": 2}).encode()),
               (200, {}, b"{}")]

    def fake_http(url, method="GET", body=None, headers=None, timeout=None):
        assert url.endswith("/internal/import-stream")
        bodies.append(bytes(body))
        return replies.pop(0)

    monkeypatch.setattr(client, "_http", fake_http)
    monkeypatch.setattr("pilosa_tpu.server.httpclient.time.sleep",
                        lambda s: None)
    assert client.send_import_stream(peer, reqs) == 3
    assert len(bodies) == 2
    first = [wire.decode_import(f) for f in
             wire.iter_stream_frames(io.BytesIO(bodies[0]).read)]
    resumed = [wire.decode_import(f) for f in
               wire.iter_stream_frames(io.BytesIO(bodies[1]).read)]
    assert [r["shard"] for r in first] == [0, 1, 2]
    assert [r["shard"] for r in resumed] == [2]


def test_send_import_stream_zero_progress_raises(monkeypatch):
    client = HTTPInternalClient()
    peer = Node(id="p1", uri=URI(host="127.0.0.1", port=1))

    def always_429(url, method="GET", body=None, headers=None, timeout=None):
        return 429, {"Retry-After": "0"}, json.dumps({"applied": 0}).encode()

    monkeypatch.setattr(client, "_http", always_429)
    monkeypatch.setattr("pilosa_tpu.server.httpclient.time.sleep",
                        lambda s: None)
    with pytest.raises(NodeHTTPError) as ei:
        client.send_import_stream(peer, [_value_req(0, [1], [2])])
    assert ei.value.code == 429


def test_send_import_stream_old_peer_fallback(monkeypatch):
    """404 from a peer that predates the route: the whole stream is
    replayed per-request through _post_import and the peer is
    remembered — the next stream skips the probe entirely."""
    client = HTTPInternalClient()
    peer = Node(id="old1", uri=URI(host="127.0.0.1", port=1))
    reqs = [_value_req(s, [s], [s]) for s in range(3)]
    http_calls, posted = [], []

    def fake_http(url, method="GET", body=None, headers=None, timeout=None):
        http_calls.append(url)
        return 404, {}, b'{"error": "not found"}'

    monkeypatch.setattr(client, "_http", fake_http)
    monkeypatch.setattr(client, "_post_import",
                        lambda node, r, json_only=False: posted.append(r))
    assert client.send_import_stream(peer, reqs) == 3
    assert len(http_calls) == 1 and len(posted) == 3
    assert peer.id in client._stream_unsupported
    assert client.send_import_stream(peer, reqs) == 3
    assert len(http_calls) == 1  # no second probe
    assert len(posted) == 6


# -- coordinator routing (vectorized shard split + stream fan-out) -----------


def test_route_import_shard_split_and_stream(monkeypatch):
    """Columns straddling odd shard boundaries reach the right owners
    with LWW order preserved, and a multi-shard remote fan-out goes out
    as ONE import stream per peer."""
    lc = LocalCluster(2, replica_n=1)
    lc.create_index("ri")
    from pilosa_tpu.core.field import FieldOptions
    lc.create_field("ri", "v", FieldOptions(
        type="int", min=-1000, max=1000))
    api = API(lc[0].holder, lc[0].executor, cluster=lc[0].cluster)
    streams = []
    orig_send = lc.client.send_import

    def spy_stream(node, reqs):
        streams.append((node.id, [int(r["shard"]) for r in reqs]))
        for r in reqs:
            orig_send(node, r["index"], r["field"], r["shard"],
                      rows=r["rowIDs"], cols=r["columnIDs"],
                      values=r["values"], timestamps=r.get("timestamps"),
                      clear=r["clear"])
        return len(reqs)

    monkeypatch.setattr(lc.client, "send_import_stream", spy_stream,
                        raising=False)
    cols = [0, SHARD_WIDTH - 1, SHARD_WIDTH, SHARD_WIDTH + 1,
            5 * SHARD_WIDTH - 1, 5 * SHARD_WIDTH,
            SHARD_WIDTH, 7]  # duplicates: LWW within shard
    vals = [1, 2, 3, 4, 5, 6, -33, 7]
    api.import_values("ri", "v", cols, vals)
    # duplicate column SHARD_WIDTH: the later value (-33) wins
    expect = {0: 1, SHARD_WIDTH - 1: 2, SHARD_WIDTH: -33,
              SHARD_WIDTH + 1: 4, 5 * SHARD_WIDTH - 1: 5,
              5 * SHARD_WIDTH: 6, 7: 7}
    got = {}
    for shard in (0, 1, 4, 5):
        for cn in lc.nodes:
            frag = cn.holder.fragment("ri", "v", "bsig_v", shard)
            if frag is None:
                continue
            for c, v in expect.items():
                if c // SHARD_WIDTH == shard:
                    val, ok = frag.value(c, 11)
                    assert ok and val == v, (shard, c, val, v)
                    got[c] = val
    assert got == expect
    # remote fan-out used the stream (node1 owns >1 shard with rf=1 only
    # if placement says so; assert any stream seen had its shards sorted
    # through one call per peer)
    for node_id, shards in streams:
        assert node_id != "node0"
        assert len(shards) == len(set(shards))


def test_route_import_bits_epoch_timestamps():
    """Routed bit imports carry epoch ints end-to-end (the remote peer
    re-parses them into time views identically to local application)."""
    lc = LocalCluster(2, replica_n=1)
    lc.create_index("ti2")
    from pilosa_tpu.core.field import FieldOptions
    lc.create_field("ti2", "t", FieldOptions(type="time", time_quantum="YMD"))
    api = API(lc[0].holder, lc[0].executor, cluster=lc[0].cluster)
    cols = [5, SHARD_WIDTH + 6, 3 * SHARD_WIDTH + 7]
    api.import_bits("ti2", "t", [1, 1, 1], cols,
                    timestamps=[1700000000, None, 1700000000])
    r = lc.query("ti2", "Row(t=1)")[0]
    assert sorted(int(c) for c in r.columns()) == sorted(cols)
    r = lc.query(
        "ti2", "Row(t=1, from='2023-11-14T00:00', to='2023-11-16T00:00')")[0]
    assert sorted(int(c) for c in r.columns()) == [5, 3 * SHARD_WIDTH + 7]


# -- ingest/query isolation drill --------------------------------------------


@pytest.mark.slow
def test_ingest_under_query_drill():
    """Deterministic isolation drill: interactive p99 while a bulk
    import stream hammers the node must stay within 3x the no-ingest
    baseline, with ZERO failed queries; backpressure (429) is allowed
    and counted."""
    n = ServerNode(bind="127.0.0.1:0", use_planner=False,
                   qos_max_concurrent=4, ingest_max_inflight_mb=1)
    n.open()
    client = HTTPInternalClient(timeout=30)
    try:
        b = n.address
        req(b, "POST", "/index/drill", "{}")
        req(b, "POST", "/index/drill/field/f", "{}")
        req(b, "POST", "/index/drill/field/v",
            json.dumps({"options": {"type": "int", "min": -100_000,
                                    "max": 100_000}}))
        rng = np.random.default_rng(7)
        body = json.dumps({
            "rowIDs": rng.integers(0, 8, 5000).tolist(),
            "columnIDs": rng.integers(0, 4 * SHARD_WIDTH, 5000).tolist()})
        assert req(b, "POST", "/index/drill/field/f/import", body)[0] == 200

        def run_queries(k):
            lat = LogHistogram(bounds=[1e-5 * (2 ** (i / 4))
                                       for i in range(84)])
            fails = 0
            for i in range(k):
                t0 = time.perf_counter()
                status, resp, _ = req(b, "POST", "/index/drill/query",
                                      f"Count(Row(f={i % 8}))")
                lat.observe(time.perf_counter() - t0)
                if status != 200 or "results" not in resp:
                    fails += 1
            return lat.quantile(0.99), fails

        # warm the query path, then baseline
        run_queries(10)
        base_p99, base_fails = run_queries(60)
        assert base_fails == 0

        stop = threading.Event()
        backpressured = [0]
        chunks_sent = [0]

        def ingest():
            node_ref = _client_node(n)
            s = 0
            while not stop.is_set():
                reqs = [_value_req(
                    (s + j) % 8,
                    (((s + j) % 8) * SHARD_WIDTH
                     + rng.integers(0, SHARD_WIDTH, 2000,
                                    dtype=np.int64)).tolist(),
                    rng.integers(-1000, 1000, 2000).tolist(),
                    index="drill")
                    for j in range(4)]
                try:
                    applied = client.send_import_stream(node_ref, reqs)
                    chunks_sent[0] += applied
                except NodeHTTPError as e:
                    if e.code == 429:
                        backpressured[0] += 1
                    else:
                        raise
                s += 4

        t = threading.Thread(target=ingest, daemon=True)
        t.start()
        try:
            load_p99, load_fails = run_queries(60)
        finally:
            stop.set()
            t.join(timeout=30)
        assert load_fails == 0, "interactive queries failed under ingest"
        assert chunks_sent[0] > 0, "ingest thread made no progress"
        floor = 0.05  # absolute floor: empty-node baselines are ~µs noisy
        assert load_p99 <= max(3 * base_p99, floor), \
            (load_p99, base_p99, backpressured[0])
    finally:
        client.close()
        n.close()
