"""Traffic-harness tests: seeded determinism, the open-loop property,
zipf mix skew, SLO report schema round-trip, and cross-node exemplar
resolution (a p99 trace id resolves to a full profile from ANY node,
not just the coordinator that retained it)."""

import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from pilosa_tpu.loadgen import (OpenLoopArrivals, Scenario, QueryLeg,
                                IngestLeg, ZipfPicker, zipf_weights,
                                run_scenario, validate_report)
from pilosa_tpu.loadgen.engine import build_ops
from pilosa_tpu.loadgen.target import ManagedTarget, QueryOutcome
from pilosa_tpu.obs import tracing


# -- arrival process ---------------------------------------------------------


def test_arrival_schedule_deterministic():
    a = OpenLoopArrivals(rate=200.0, duration_s=5.0, seed=9)
    s1, s2 = a.schedule(), a.schedule()
    np.testing.assert_array_equal(s1, s2)
    s3 = OpenLoopArrivals(rate=200.0, duration_s=5.0, seed=10).schedule()
    assert not np.array_equal(s1, s3)


def test_arrival_schedule_sorted_bounded_and_on_rate():
    a = OpenLoopArrivals(rate=500.0, duration_s=4.0, seed=3)
    s = a.schedule()
    assert np.all(np.diff(s) >= 0)
    assert s[-1] < 4.0 and s[0] >= 0.0
    # ~2000 expected arrivals; Poisson noise is ~sqrt(2000) ≈ 45
    assert abs(len(s) - 2000) < 200


def test_arrival_gamma_cv_controls_burstiness():
    def cv_of(process, cv=1.0):
        s = OpenLoopArrivals(rate=400.0, duration_s=10.0, process=process,
                             cv=cv, seed=5).schedule()
        gaps = np.diff(s)
        return float(np.std(gaps) / np.mean(gaps))

    assert abs(cv_of("poisson") - 1.0) < 0.1
    assert abs(cv_of("gamma", cv=2.0) - 2.0) < 0.3
    assert cv_of("uniform") < 1e-9


def test_arrival_validation():
    with pytest.raises(ValueError):
        OpenLoopArrivals(rate=0.0, duration_s=1.0)
    with pytest.raises(ValueError):
        OpenLoopArrivals(rate=1.0, duration_s=-1.0)
    with pytest.raises(ValueError):
        OpenLoopArrivals(rate=1.0, duration_s=1.0, process="closed")
    with pytest.raises(ValueError):
        OpenLoopArrivals(rate=1.0, duration_s=1.0, process="gamma", cv=0.0)


# -- zipf mix ----------------------------------------------------------------


def test_zipf_weights_shape():
    w = zipf_weights(16, 1.2)
    assert len(w) == 16
    assert abs(sum(w) - 1.0) < 1e-9
    assert all(a >= b for a, b in zip(w, w[1:]))
    # ratio between rank 1 and rank 4 is 4^s
    assert abs(w[0] / w[3] - 4.0 ** 1.2) < 1e-9


def test_zipf_picker_skew_matches_s():
    s_cfg = 1.3
    n = 32
    picker = ZipfPicker(n, s_cfg)
    rng = np.random.default_rng(17)
    draws = np.array([picker.pick(rng) for _ in range(20_000)])
    freq = np.bincount(draws, minlength=n) / len(draws)
    want = np.array(zipf_weights(n, s_cfg))
    # top ranks carry the mass; they must match the analytic weights
    assert np.allclose(freq[:8], want[:8], rtol=0.15)
    # recover s from the top-of-the-curve log-log slope
    ranks = np.arange(1, 9)
    slope = np.polyfit(np.log(ranks), np.log(freq[:8]), 1)[0]
    assert abs(-slope - s_cfg) < 0.2


# -- deterministic op sequence ----------------------------------------------


def _tiny_scenario(**over):
    kw = dict(
        name="tiny", seed=5, duration_s=1.5, rate=40.0,
        nodes=1, shards=2, rows=8, density=0.002,
        tenants=4, tenant_s=1.1,
        legs=[QueryLeg(name="dash", weight=3.0, kind="dashboard",
                       qos_class="interactive", population=8),
              QueryLeg(name="adhoc", weight=1.0, kind="adhoc",
                       qos_class="batch", population=16, no_cache=True)],
        max_workers=64, warmup_queries=0)
    kw.update(over)
    return Scenario(**kw)


def test_build_ops_seed_deterministic():
    sc = _tiny_scenario()
    ops1, ops2 = build_ops(sc), build_ops(sc)
    assert ops1 == ops2
    assert len(ops1) > 20
    assert all(a.offset <= b.offset for a, b in zip(ops1, ops1[1:]))
    assert {op.leg for op in ops1} == {"dash", "adhoc"}
    ops3 = build_ops(_tiny_scenario(seed=6))
    assert [o.pql for o in ops3] != [o.pql for o in ops1]


def test_scenario_dict_roundtrip():
    sc = _tiny_scenario(ingest=IngestLeg(duty=0.4, shards=1, per_shard=100))
    sc2 = Scenario.from_dict(json.loads(json.dumps(sc.to_dict())))
    assert sc2 == sc
    assert build_ops(sc2) == build_ops(sc)


# -- the open-loop property --------------------------------------------------


class _SlowFakeTarget:
    """A target whose every query takes ``service_s`` — a saturated
    server. An open-loop driver must keep dispatching on schedule
    anyway; a closed-loop one would throttle to the service rate."""

    def __init__(self, service_s: float):
        self.service_s = service_s
        self.mode = "fake"
        # unroutable address: the report's ring-exemplar fallback must
        # fail fast and quietly, proving the report needs no live node
        self.base_urls = ["http://127.0.0.1:9"]
        self._lock = threading.Lock()
        self.started = 0
        self.first_completion_at = None
        self.started_before_first_completion = 0
        self.t0 = time.perf_counter()

    def create_index(self, *a, **k): pass
    def create_field(self, *a, **k): pass
    def import_bits(self, *a, **k): pass
    def import_stream(self, reqs): return len(reqs)
    def metrics_text(self, node=0): return ""
    def debug_vars(self, node=0): return {}
    def resolve_profile(self, tid, node=0): return None
    def slow_peer(self, *a): return False
    def heal_peer(self, *a): return False
    def add_node(self): return False
    def remove_node(self, *a): return False
    def close(self): pass

    def query(self, index, pql, **kw):
        with self._lock:
            self.started += 1
            if self.first_completion_at is None:
                self.started_before_first_completion += 1
        time.sleep(self.service_s)
        with self._lock:
            if self.first_completion_at is None:
                self.first_completion_at = time.perf_counter() - self.t0
        return QueryOutcome("ok", 200)


def test_open_loop_arrivals_independent_of_completions():
    sc = _tiny_scenario(duration_s=1.5, rate=40.0, max_workers=96)
    fake = _SlowFakeTarget(service_s=0.5)
    rep = run_scenario(sc, target=fake)
    n_sched = rep["arrivals"]["scheduled"]
    assert rep["arrivals"]["dispatched"] == n_sched == fake.started
    # The driver held the schedule even though NOTHING completed for
    # the first 0.5 s: many arrivals were already in flight by then.
    assert fake.started_before_first_completion >= 5
    assert rep["arrivals"]["maxLagMs"] < 400
    # Latency is measured from the scheduled arrival, so the 0.5 s
    # service floor must show up in every class's p50.
    for cls in rep["perClass"].values():
        assert cls["client"]["p50Ms"] >= 450


# -- SLO report schema -------------------------------------------------------


def test_report_schema_roundtrip_and_validation():
    sc = _tiny_scenario()
    rep = run_scenario(sc, target=_SlowFakeTarget(service_s=0.001))
    assert validate_report(rep) == []
    rt = json.loads(json.dumps(rep))
    assert validate_report(rt) == []
    assert rt == rep

    bad = json.loads(json.dumps(rep))
    del bad["rates"]["shed"]
    bad["perClass"]["interactive"]["client"]["p99Ms"] = "fast"
    errs = validate_report(bad)
    assert any("rates.shed" in e for e in errs)
    assert any("p99Ms" in e for e in errs)
    bad2 = json.loads(json.dumps(rep))
    bad2["schemaVersion"] = 999
    assert any("schemaVersion" in e for e in validate_report(bad2))


def test_slo_gate_checks():
    import importlib.util
    import os
    spec = importlib.util.spec_from_file_location(
        "slo_gate", os.path.join(os.path.dirname(__file__), "..",
                                 "scripts", "slo_gate.py"))
    gate = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(gate)

    rep = {"a": {"b": 10.0}, "xs": [1, 2]}
    assert gate.run_check(rep, {"path": "a.b", "min": 5}) is None
    assert gate.run_check(rep, {"path": "a.b", "max": 5}) is not None
    assert gate.run_check(rep, {"path": "a.b", "value": 11,
                                "relTol": 0.2}) is None
    assert gate.run_check(rep, {"path": "a.b", "value": 20,
                                "relTol": 0.2}) is not None
    assert gate.run_check(rep, {"path": "a.b", "value": 0,
                                "absTol": 15}) is None
    assert gate.run_check(rep, {"path": "xs", "minLen": 2}) is None
    assert gate.run_check(rep, {"path": "xs", "minLen": 3}) is not None
    assert gate.run_check(rep, {"path": "a.missing", "min": 0}) is not None


# -- end-to-end: one real managed run ----------------------------------------


def test_scenario_end_to_end_single_node():
    sc = _tiny_scenario(
        duration_s=2.5, rate=30.0, shards=2, density=0.003,
        warmup_queries=4,
        ingest=IngestLeg(duty=0.3, shards=1, per_shard=2_000))
    rep = run_scenario(sc)   # run_scenario enforces the schema itself
    assert rep["target"]["mode"] == "managed"
    inter = rep["perClass"]["interactive"]
    assert inter["counts"]["ok"] > 10
    assert inter["client"]["count"] > 10
    assert rep["legs"]["dash"]["count"] > 0
    assert rep["legs"]["adhoc"]["count"] > 0
    assert rep["cache"]["hits"] + rep["cache"]["misses"] > 0
    assert rep["ingest"]["batches"] >= 1
    assert rep["ingest"]["errors"] == 0
    # a report always links at least one resolved profile
    assert len(rep["exemplars"]) >= 1
    assert rep["exemplars"][0]["traceId"]
    assert isinstance(rep["exemplars"][0]["profile"], dict)


# -- cross-node exemplar resolution (the profile-ring fan-out) ---------------


def test_exemplar_profile_resolves_from_any_node():
    """A fanned-out query's profile is retained on the coordinator's
    ring only. /debug/queries/<trace-id> on ANY node must resolve it
    (one-hop peer fan-out), with the nested remote legs intact."""
    t = ManagedTarget(n_nodes=3, replica_n=1)
    try:
        t.create_index("xn")
        t.create_field("xn", "f")
        from pilosa_tpu.config import SHARD_WIDTH
        rng = np.random.default_rng(2)
        for s in range(6):
            cols = s * SHARD_WIDTH + rng.integers(
                0, SHARD_WIDTH, 500).astype(np.uint64)
            rows = rng.integers(0, 4, 500).astype(np.uint64)
            t.import_bits("xn", "f", rows, cols)
        tid = tracing.new_trace_id()
        out = t.query("xn", "Count(Row(f=1))", trace_id=tid, no_cache=True)
        assert out.status == "ok"

        # the serving node retained it; every OTHER node must resolve
        # it through the fan-out rather than 404ing
        for node in range(3):
            prof = t.resolve_profile(tid, node=node)
            assert prof is not None, f"node {node} failed to resolve {tid}"
            assert prof.get("traceId") == tid
        # a 3-node fan-out leaves remote legs in the retained profile
        prof = t.resolve_profile(tid, node=1)
        assert prof.get("remoteLegs"), "nested remote legs missing"

        # the loop guard: ?local=true never fans out, so at least one
        # node (any ring that didn't serve the query) answers 404
        local_misses = 0
        for node in range(3):
            try:
                urllib.request.urlopen(
                    f"{t.base_urls[node]}/debug/queries/{tid}?local=true",
                    timeout=10).read()
            except urllib.error.HTTPError as e:
                assert e.code == 404
                local_misses += 1
        assert local_misses == 2, "exactly one ring should hold the trace"
    finally:
        t.close()
