"""End-to-end data integrity: corruption detection at load, quarantine
with evidence preservation, replica routing, scrubber self-healing, and
the operator surface (/debug/quarantine, 503 on corrupt-no-replica).

Models the acceptance scenario of the integrity subsystem: a bit-flipped
snapshot on one node of a replica_n=2 cluster must never produce a wrong
answer — queries route to the clean replica while the scrubber rebuilds
the local copy from consensus and re-snapshots it.
"""

import json
import os
import urllib.error
import urllib.request

import pytest

from pilosa_tpu.cluster.harness import LocalCluster
from pilosa_tpu.obs.stats import MemoryStats
from pilosa_tpu.storage.diskstore import DiskStore
from pilosa_tpu.storage.faults import corrupt_file

N_BITS = 50
N_ROWS = 5  # Count(Row(f=r)) == 10 for every r


def seed_and_close(data_dirs):
    """2-node replica_n=2 cluster: write 50 bits, snapshot, shut down."""
    lc = LocalCluster(2, replica_n=2, data_dirs=data_dirs)
    lc.create_index("i")
    lc.create_field("i", "f")
    for c in range(N_BITS):
        lc.query("i", f"Set({c}, f={c % N_ROWS})")
    for cn in lc.nodes:
        cn.store.save_schema()
        cn.store.close()


def stats_factory(registry):
    """store_factory that gives every node's store a MemoryStats the
    test can read back (keyed by data dir)."""
    def factory(data_dir, holder):
        s = MemoryStats()
        registry[os.path.basename(data_dir)] = s
        return DiskStore(data_dir, holder, stats=s)
    return factory


def test_cluster_bitflip_routed_then_scrub_repairs(tmp_path):
    """The acceptance path: bit-flip node0's snapshot → detected at load,
    quarantined (evidence preserved), queries stay correct via the
    replica, scrub repairs + re-snapshots, restart loads clean."""
    dirs = [str(tmp_path / "n0"), str(tmp_path / "n1")]
    seed_and_close(dirs)

    snap = os.path.join(dirs[0], "i", "f", "standard", "0.snap")
    assert os.path.exists(snap)
    corrupt_file(snap, "bitflip")

    stats = {}
    lc = LocalCluster(2, replica_n=2, data_dirs=dirs,
                      store_factory=stats_factory(stats))

    # Detected at load: quarantined, file preserved, reads routed away.
    key = ("i", "f", "standard", 0)
    entry = lc[0].store.quarantine.get(key)
    assert entry is not None and entry["state"] == "routed"
    assert os.path.exists(snap + ".quarantine")
    assert not os.path.exists(snap)
    assert stats["n0"].counter_value("integrity.quarantined") == 1
    assert lc[1].store.quarantine.get(key) is None

    # Every query over the shard is CORRECT via the replica, from both
    # coordinators, with zero failures.
    for node in (0, 1):
        for r in range(N_ROWS):
            (got,) = lc.query("i", f"Count(Row(f={r}))", node=node)
            assert got == N_BITS // N_ROWS, (node, r)

    # Scrub: rebuild from replica consensus, re-snapshot, release.
    out = lc[0].scrubber.scrub_pass()
    assert out["repaired"] == 1 and out["released"] == 1
    assert len(lc[0].store.quarantine) == 0
    assert stats["n0"].counter_value("integrity.released") == 1
    assert lc[0].store.verify_snapshot(key) == "ok"
    # Repaired fragment serves locally again.
    (got,) = lc.query("i", "Count(Row(f=1))", node=0, cache=False)
    assert got == N_BITS // N_ROWS

    for cn in lc.nodes:
        cn.store.close()

    # Restart node0: the repaired snapshot loads clean.
    stats2 = {}
    lc2 = LocalCluster(2, replica_n=2, data_dirs=dirs,
                       store_factory=stats_factory(stats2))
    assert len(lc2[0].store.quarantine) == 0
    assert stats2["n0"].counter_value("integrity.quarantined") == 0
    (got,) = lc2.query("i", "Count(Row(f=1))", node=0)
    assert got == N_BITS // N_ROWS
    for cn in lc2.nodes:
        cn.store.close()


def test_scrub_pass_catches_latent_bit_rot(tmp_path):
    """Disk rots AFTER a clean load: the periodic re-verification walk
    finds the bad footer and re-snapshots from the in-memory truth."""
    dirs = [str(tmp_path / "n0"), str(tmp_path / "n1")]
    seed_and_close(dirs)
    lc = LocalCluster(2, replica_n=2, data_dirs=dirs)
    snap = os.path.join(dirs[0], "i", "f", "standard", "0.snap")
    corrupt_file(snap, "bitflip")  # memory still healthy

    out = lc[0].scrubber.scrub_pass()
    assert out["bad"] == 1
    assert lc[0].store.verify_snapshot(("i", "f", "standard", 0)) == "ok"
    # Memory was never corrupted, so queries were right throughout.
    (got,) = lc.query("i", "Count(Row(f=1))", node=0)
    assert got == N_BITS // N_ROWS
    for cn in lc.nodes:
        cn.store.close()


def test_scrubber_skips_when_qos_sheds(tmp_path):
    """Scrub work admits as CLASS_INTERNAL; a saturated admission gate
    sheds it (counted, retried next pass) instead of queueing behind it."""
    from pilosa_tpu.cluster.scrub import Scrubber
    from pilosa_tpu.qos.admission import AdmissionController

    dirs = [str(tmp_path / "n0"), str(tmp_path / "n1")]
    seed_and_close(dirs)
    snap = os.path.join(dirs[0], "i", "f", "standard", "0.snap")
    corrupt_file(snap, "bitflip")
    lc = LocalCluster(2, replica_n=2, data_dirs=dirs)

    stats = MemoryStats()
    adm = AdmissionController(max_concurrent=1, max_queue=0,
                              internal_reserve=0)
    scrub = Scrubber(lc[0].holder, lc[0].cluster, lc[0].cluster.client,
                     lc[0].store, stats=stats, admission=adm)
    with adm.admit("interactive"):  # gate full: internal work sheds
        out = scrub.scrub_pass()
    assert out["repaired"] == 0
    assert stats.counter_value("integrity.scrubShed") >= 1
    assert len(lc[0].store.quarantine) == 1  # retried next pass

    out = scrub.scrub_pass()  # gate free again
    assert out["repaired"] == 1
    assert len(lc[0].store.quarantine) == 0
    for cn in lc.nodes:
        cn.store.close()


# -- operator surface: HTTP ------------------------------------------------

def _req(base, path, body=None, method=None):
    r = urllib.request.Request(
        base + path, data=(body.encode() if body is not None else None),
        method=method or ("POST" if body is not None else "GET"))
    return json.loads(urllib.request.urlopen(r, timeout=10).read() or b"{}")


def test_debug_quarantine_endpoint_and_503(tmp_path):
    """Standalone node, snapshot corrupted, WAL empty: no clean copy
    anywhere → /debug/quarantine lists the shard as unavailable and a
    query over it fails 503, never silently serving zeros."""
    from pilosa_tpu.server.node import ServerNode

    d = str(tmp_path / "data")
    n = ServerNode(bind="127.0.0.1:0", use_planner=False, data_dir=d,
                   scrub_interval=0)
    n.open()
    _req(n.address, "/index/i", "{}")
    _req(n.address, "/index/i/field/f", "{}")
    _req(n.address, "/index/i/query", "Set(123, f=1)")
    n.close()  # snapshot published, WAL truncated

    corrupt_file(os.path.join(d, "i", "f", "standard", "0.snap"), "bitflip")
    n2 = ServerNode(bind="127.0.0.1:0", use_planner=False, data_dir=d,
                    scrub_interval=0)
    n2.open()
    try:
        q = _req(n2.address, "/debug/quarantine")
        assert q["count"] == 1
        (e,) = q["entries"]
        assert (e["index"], e["field"], e["shard"]) == ("i", "f", 0)
        assert e["state"] == "unavailable"
        assert e["files"] and all(f.endswith(".quarantine")
                                  for f in e["files"])
        with pytest.raises(urllib.error.HTTPError) as exc:
            _req(n2.address, "/index/i/query", "Row(f=1)")
        assert exc.value.code == 503
        assert "quarantined" in exc.value.read().decode()
    finally:
        n2.close()


def test_standalone_degraded_serves_wal_salvage(tmp_path):
    """Snapshot corrupt but the WAL holds the ops: standalone degrades to
    WAL-only replay (partial truth beats an error beats silent zeros)
    and /debug/quarantine says so; queries still answer."""
    from pilosa_tpu.core import Holder
    from pilosa_tpu.exec import Executor
    from pilosa_tpu.server.node import ServerNode

    # Crash shape: WAL on disk, no snapshot taken (store never closed) —
    # then fabricate a corrupt snapshot next to it.
    d = str(tmp_path / "data")
    h = Holder()
    store = DiskStore(d, h)
    store.open()
    h.create_index("i").create_field("f")
    Executor(h).execute("i", "Set(7, f=1) Set(9, f=1)")
    store.save_schema()
    snap = os.path.join(d, "i", "f", "standard", "0.snap")
    with open(snap, "wb") as f:
        f.write(b"\x00" * 64)  # unreadable garbage
    wal = os.path.join(d, "i", "f", "standard", "0.wal")
    assert os.path.getsize(wal) > 0

    n2 = ServerNode(bind="127.0.0.1:0", use_planner=False, data_dir=d,
                    scrub_interval=0)
    n2.open()
    try:
        q = _req(n2.address, "/debug/quarantine")
        assert q["count"] == 1
        assert q["entries"][0]["state"] == "degraded"
        out = _req(n2.address, "/index/i/query", "Row(f=1)")
        assert out["results"][0]["columns"] == [7, 9]
        # Standalone scrub: persists the salvage, releases quarantine.
        res = n2.scrubber.scrub_pass()
        assert res["repaired"] == 1
        assert _req(n2.address, "/debug/quarantine")["count"] == 0
    finally:
        n2.close()
