"""Elastic resize + failure-detector tests.

Models cluster_internal_test.go's fragSources cases and the clustertests
node add/remove flows.
"""

import numpy as np
import pytest

from pilosa_tpu.cluster import Cluster, Node
from pilosa_tpu.cluster.harness import LocalCluster
from pilosa_tpu.cluster.node import URI
from pilosa_tpu.cluster.resize import (
    ResizeJob,
    check_nodes,
    fragment_sources,
)
from pilosa_tpu.config import SHARD_WIDTH


def test_fragment_sources_pure():
    old = Cluster("a", [Node(id="a"), Node(id="b")], replica_n=1)
    new = Cluster("a", [Node(id="a"), Node(id="b"), Node(id="c")], replica_n=1)
    frags = [("i", "f", "standard", s) for s in range(20)]
    srcs = fragment_sources(old, new, frags)
    # only node c (the new node) fetches anything, and only shards it now owns
    assert set(srcs) <= {"c"}
    for s in srcs.get("c", []):
        assert new.shard_nodes("i", s.shard)[0].id == "c"
        assert s.source_node in ("a", "b")


def seed(lc: LocalCluster, n_shards=6):
    lc.create_index("i")
    lc.create_field("i", "f")
    cols = [s * SHARD_WIDTH + s for s in range(n_shards)]
    for c in cols:
        lc.query("i", f"Set({c}, f=1)")
    return cols


def test_grow_cluster_in_process():
    lc = LocalCluster(2)
    cols = seed(lc)
    assert lc.query("i", "Count(Row(f=1))") == [len(cols)]

    # Boot a third node and join it.
    from pilosa_tpu.cluster.harness import ClusterNode
    from pilosa_tpu.cluster.cluster import STATE_NORMAL
    new_member = Node(id="node2", uri=URI(port=10103))
    member_list = [Node(id=n.id, uri=n.uri) for n in lc[0].cluster.nodes]
    c2 = Cluster("node2", member_list + [new_member], replica_n=1,
                 client=lc.client)
    c2.set_state(STATE_NORMAL)
    cn2 = ClusterNode("node2", c2)
    cn2.apply_schema(lc[0].holder.schema())
    lc.client.register("node2", cn2)
    lc.nodes.append(cn2)

    job = ResizeJob(lc[0].cluster, lc[0].holder, lc.client)
    state = job.run([Node(id=n.id, uri=n.uri) for n in lc[0].cluster.nodes]
                    + [new_member])
    assert state == "DONE"
    assert len(lc[0].cluster.nodes) == 3
    # All data still reachable, from any coordinator.
    for node in range(3):
        assert lc.query("i", "Count(Row(f=1))", node=node) == [len(cols)]


def test_shrink_cluster_in_process():
    lc = LocalCluster(3, replica_n=2)
    cols = seed(lc)
    victim = "node2"
    keep = [Node(id=n.id, uri=n.uri, is_coordinator=n.is_coordinator)
            for n in lc[0].cluster.nodes if n.id != victim]
    job = ResizeJob(lc[0].cluster, lc[0].holder, lc.client)
    assert job.run(keep) == "DONE"
    lc.client.down.add(victim)  # victim actually gone
    for node in range(2):
        assert lc.query("i", "Count(Row(f=1))", node=node) == [len(cols)]


def test_resize_abort():
    lc = LocalCluster(2)
    seed(lc)
    job = ResizeJob(lc[0].cluster, lc[0].holder, lc.client)
    job.abort()
    state = job.run([Node(id=n.id, uri=n.uri) for n in lc[0].cluster.nodes]
                    + [Node(id="nodeX", uri=URI(port=10199))])
    assert state == "ABORTED"
    assert len(lc[0].cluster.nodes) == 2  # membership unchanged


def test_check_nodes_failure_detector():
    lc = LocalCluster(3, replica_n=2)
    c0 = lc[0].cluster
    assert check_nodes(c0, lc.client) == []
    lc.client.down.add("node1")
    changed = check_nodes(c0, lc.client)
    assert changed == ["node1"]
    assert c0.node_by_id("node1").state == "DOWN"
    assert c0.state == "DEGRADED"
    lc.client.down.discard("node1")
    assert check_nodes(c0, lc.client) == ["node1"]
    assert c0.state == "NORMAL"


def test_http_resize_remove_node():
    """Full HTTP flow: 3 servers, coordinator removes one via the REST
    resize route, data remains queryable."""
    import json
    import socket
    import urllib.error
    import urllib.request
    from pilosa_tpu.server.node import ServerNode

    ports = []
    for _ in range(3):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        ports.append(s.getsockname()[1])
        s.close()
    addrs = [f"127.0.0.1:{p}" for p in ports]
    nodes = [ServerNode(bind=a, peers=[x for x in addrs if x != a],
                        replica_n=2, use_planner=False) for a in addrs]
    for n in nodes:
        n.open()
    try:
        base = nodes[0].address

        def post(path, body):
            r = urllib.request.Request(base + path, data=body.encode(),
                                       method="POST")
            return json.loads(urllib.request.urlopen(r, timeout=10).read()
                              or b"{}")

        post("/index/i", "{}")
        post("/index/i/field/f", "{}")
        cols = [s * SHARD_WIDTH for s in range(5)]
        for c in cols:
            post("/index/i/query", f"Set({c}, f=1)")
        assert post("/index/i/query", "Count(Row(f=1))") == \
            {"results": [len(cols)]}

        # Removals only run on the coordinator (reference
        # cluster.go:1870: non-coordinators refuse, naming it). Find it
        # from /status and never remove it or the node we query.
        st = json.loads(urllib.request.urlopen(base + "/status",
                                               timeout=10).read())
        coord_id = next(n["id"] for n in st["nodes"] if n["isCoordinator"])
        coord_base = f"http://{coord_id}"
        victim = next(a for a in sorted(addrs, reverse=True)
                      if a != coord_id and a != addrs[0])
        # A non-coordinator refuses with the coordinator's address.
        non_coord = next(a for a in addrs if a != coord_id)
        try:
            r = urllib.request.Request(
                f"http://{non_coord}/cluster/resize/remove-node",
                data=json.dumps({"id": victim}).encode(), method="POST")
            urllib.request.urlopen(r, timeout=10)
            raise AssertionError("non-coordinator accepted a removal")
        except urllib.error.HTTPError as e:
            assert coord_id in e.read().decode()
        r = urllib.request.Request(
            coord_base + "/cluster/resize/remove-node",
            data=json.dumps({"id": victim}).encode(), method="POST")
        urllib.request.urlopen(r, timeout=60).read()
        st = json.loads(urllib.request.urlopen(base + "/status",
                                               timeout=10).read())
        assert len(st["nodes"]) == 2
        # The removed node received the commit too (ADVICE r4 #1): it
        # must sit in the terminal REMOVED state with its API gate
        # closed — not reopen as a zombie serving the stale ring.
        vst = json.loads(urllib.request.urlopen(
            f"http://{victim}/status", timeout=10).read())
        assert vst["state"] == "REMOVED"
        try:
            r = urllib.request.Request(
                f"http://{victim}/index/i/query",
                data=b"Count(Row(f=1))", method="POST")
            urllib.request.urlopen(r, timeout=10)
            raise AssertionError("removed node still serves queries")
        except urllib.error.HTTPError as e:
            assert e.code in (400, 405, 409, 503)
        nodes[[i for i, a in enumerate(addrs) if a == victim][0]].close()
        assert post("/index/i/query", "Count(Row(f=1))") == \
            {"results": [len(cols)]}
    finally:
        for n in nodes:
            try:
                n.close()
            except Exception:
                pass


def _free_ports(n):
    import socket
    ports = []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        ports.append(s.getsockname()[1])
        s.close()
    return ports


def test_http_dynamic_join():
    """A fresh node joins a RUNNING 2-node cluster over HTTP with no
    peer restarts: the coordinator resizes it in (schema + fragments
    stream over) and broadcasts the ring; queries then fan out to it
    (VERDICT r2 missing #1 / next #6)."""
    import json
    import time
    import urllib.request
    from pilosa_tpu.server.node import ServerNode

    ports = _free_ports(3)
    addrs = [f"127.0.0.1:{p}" for p in ports]
    nodes = [ServerNode(bind=a, peers=[x for x in addrs[:2] if x != a],
                        replica_n=1, use_planner=False,
                        anti_entropy_interval=0.0, check_nodes_interval=0.0)
             for a in addrs[:2]]
    for n in nodes:
        n.open()
    joiner = None
    try:
        base = nodes[0].address

        def post(path, body):
            r = urllib.request.Request(base + path, data=body.encode(),
                                       method="POST")
            return json.loads(urllib.request.urlopen(r, timeout=10).read()
                              or b"{}")

        post("/index/i", "{}")
        post("/index/i/field/f", "{}")
        cols = [s * SHARD_WIDTH for s in range(8)]
        for c in cols:
            post("/index/i/query", f"Set({c}, f=1)")

        # Boot the third node pointing at a RUNNING member (not even the
        # coordinator — the join forwards).
        joiner = ServerNode(bind=addrs[2], join=addrs[1],
                            use_planner=False, anti_entropy_interval=0.0,
                            check_nodes_interval=0.0)
        joiner.open()
        for _ in range(100):
            if len(joiner.cluster.nodes) == 3:
                break
            time.sleep(0.1)
        assert len(joiner.cluster.nodes) == 3
        st = json.loads(urllib.request.urlopen(base + "/status",
                                               timeout=10).read())
        assert len(st["nodes"]) == 3
        # The ring now includes the joiner; data still complete.
        assert post("/index/i/query", "Count(Row(f=1))") == \
            {"results": [len(cols)]}
        # And queries through the JOINER see the whole index too.
        r = urllib.request.Request(joiner.address + "/index/i/query",
                                   data=b"Count(Row(f=1))", method="POST")
        assert json.loads(urllib.request.urlopen(r, timeout=10).read()) == \
            {"results": [len(cols)]}
    finally:
        for n in nodes + ([joiner] if joiner else []):
            try:
                n.close()
            except Exception:
                pass


def test_resize_failure_keeps_old_topology():
    """A target failing mid-resize must leave the OLD topology live
    (per-target completion ACKs before commit; reference
    ResizeInstructionComplete cluster.go:1315)."""
    lc = LocalCluster(2)
    seed(lc)
    old_nodes = list(lc[0].cluster.nodes)
    # The new node is unreachable: its resize instruction must fail.
    new = old_nodes + [Node(id="nodeX", uri=URI(port=10199))]
    lc.client.down.add("nodeX")
    job = ResizeJob(lc[0].cluster, lc[0].holder, lc.client)
    state = job.run([Node(id=n.id, uri=n.uri, is_coordinator=n.is_coordinator)
                     for n in new])
    assert state == "FAILED"
    assert job.failed == ["nodeX"]
    assert [n.id for n in lc[0].cluster.nodes] == [n.id for n in old_nodes]
    assert lc[0].cluster.state == "NORMAL"
    # Data still fully queryable through the old ring.
    assert lc.query("i", "Count(Row(f=1))") == [6]


def test_autonomous_recovery_after_restart():
    """VERDICT r2 #10: with default tickers ON, a node that dies and
    comes back converges with NO operator action — the failure detector
    marks it DOWN then READY, and anti-entropy repairs the writes it
    missed."""
    import json
    import time
    import urllib.request
    from pilosa_tpu.server.node import ServerNode

    ports = _free_ports(2)
    addrs = [f"127.0.0.1:{p}" for p in ports]
    nodes = [ServerNode(bind=a, peers=[x for x in addrs if x != a],
                        replica_n=2, use_planner=False,
                        anti_entropy_interval=0.5,
                        check_nodes_interval=0.3)
             for a in addrs]
    for n in nodes:
        n.open()
    try:
        base = nodes[0].address

        def post(path, body):
            r = urllib.request.Request(base + path, data=body.encode(),
                                       method="POST")
            return json.loads(urllib.request.urlopen(r, timeout=10).read()
                              or b"{}")

        post("/index/i", "{}")
        post("/index/i/field/f", "{}")
        post("/index/i/query", "Set(1, f=1)")

        # Kill node 1; the detector must mark it DOWN (replica_n=2 ->
        # DEGRADED) without any operator call.
        nodes[1].close()
        for _ in range(100):
            if nodes[0].cluster.state == "DEGRADED":
                break
            time.sleep(0.1)
        assert nodes[0].cluster.state == "DEGRADED"

        # Writes while the replica is down.
        post("/index/i/query", "Set(2, f=1) Set(3, f=1)")

        # Restart it on the same address (fresh process state).
        nodes[1] = ServerNode(bind=addrs[1], peers=[addrs[0]], replica_n=2,
                              use_planner=False,
                              anti_entropy_interval=0.5,
                              check_nodes_interval=0.3)
        nodes[1].open()
        # Autonomous: DOWN -> READY via check_nodes, missed bits via
        # anti-entropy — no /cluster or /sync calls issued here.
        deadline = time.time() + 20
        frag = None
        while time.time() < deadline:
            frag = nodes[1].holder.fragment("i", "f", "standard", 0)
            if (nodes[0].cluster.state == "NORMAL" and frag is not None
                    and frag.contains(1, 2) and frag.contains(1, 3)):
                break
            time.sleep(0.2)
        assert nodes[0].cluster.state == "NORMAL"
        assert frag is not None and frag.contains(1, 2) and frag.contains(1, 3)
    finally:
        for n in nodes:
            try:
                n.close()
            except Exception:
                pass


def test_streaming_fragment_transfer_constant_memory(monkeypatch, rng):
    """Resize streams fragments in bounded chunks: a fragment larger
    than the chunk budget arrives whole, and no single transfer blob
    ever exceeds the budget (VERDICT r2 missing #5)."""
    import numpy as np
    from pilosa_tpu.core.fragment import Fragment

    monkeypatch.setattr(Fragment, "TRANSFER_CHUNK_BITS", 2048)
    lc = LocalCluster(2)
    lc.create_index("i")
    lc.create_field("i", "f")
    # ~20k bits over 40 rows in shard 0 >> 2048-bit chunks.
    rows = rng.integers(0, 40, 20_000).astype(np.uint64)
    cols = rng.integers(0, SHARD_WIDTH, 20_000).astype(np.uint64)
    owner = lc[0].cluster.shard_nodes("i", 0)[0]
    src_node = lc.client.peers[owner.id]
    src_node.handle_import_request("i", "f", rows=rows, cols=cols)
    frag = src_node.holder.fragment("i", "f", "standard", 0)
    total_bits = frag.bit_count()
    assert total_bits > 8 * 2048

    # Spy on the PTS1 stream the source pushes: per-request pair counts
    # and the QoS class the migration rides under.
    sizes = []
    qos_seen = []
    orig = type(lc.client).send_import_stream

    def spy(self, node, reqs, chunked=False, qos_class=None):
        reqs = list(reqs)
        qos_seen.append(qos_class)
        sizes.extend(len(r.get("columnIDs") or []) for r in reqs
                     if r.get("kind") == "fragment")
        return orig(self, node, reqs, chunked=chunked, qos_class=qos_class)

    monkeypatch.setattr(type(lc.client), "send_import_stream", spy)

    other = [cn for cn in lc.nodes if cn.id != owner.id][0]
    from pilosa_tpu.cluster.resize import ResizeSource, apply_resize_instruction
    from dataclasses import asdict
    src = ResizeSource(source_node=owner.id, index="i", field="f",
                       view="standard", shard=0)
    apply_resize_instruction(other.holder, lc.client, other.cluster,
                             [asdict(src)])
    got = other.holder.fragment("i", "f", "standard", 0)
    assert got is not None and got.bit_count() == total_bits
    for r in range(40):
        np.testing.assert_array_equal(got.row_words(r), frag.row_words(r))
    assert len(sizes) > 4                      # really chunked
    assert max(sizes) <= 2048                  # each request bounded
    assert sum(sizes) == total_bits            # no loss, no duplication
    assert qos_seen and all(q == "internal" for q in qos_seen)


def test_fragment_sources_skips_removed_node():
    """A removed node must never be picked as a stream source — it is
    usually dead (reference cluster.go:823-826)."""
    old = Cluster("a", [Node(id="a"), Node(id="b"), Node(id="c")],
                  replica_n=2)
    new = Cluster("a", [Node(id="a"), Node(id="b")], replica_n=2)
    frags = [("i", "f", "standard", s) for s in range(32)]
    srcs = fragment_sources(old, new, frags)
    for sources in srcs.values():
        for s in sources:
            assert s.source_node != "c"


def test_fragment_sources_no_surviving_replica_errors():
    """replica_n=1 + removing a shard's only owner: the resize must
    refuse (data would be lost), like the reference's not-enough-data
    error."""
    old = Cluster("a", [Node(id="a"), Node(id="b")], replica_n=1)
    new = Cluster("a", [Node(id="a")], replica_n=1)
    # find a shard whose sole old owner is node b
    shard = next(s for s in range(64)
                 if old.shard_nodes("i", s)[0].id == "b")
    with pytest.raises(ValueError):
        fragment_sources(old, new, [("i", "f", "standard", shard)])


def test_resize_ack_deadline_marks_silent_target_failed():
    """A target that accepts the instruction but never ACKs must fail
    the job at the ACK deadline — old topology stays live."""
    lc = LocalCluster(2)
    seed(lc)

    class SilentPeer:
        def handle_message(self, message):
            pass  # swallow the instruction, never ACK

    lc.client.register("nodeX", SilentPeer())
    job = ResizeJob(lc[0].cluster, lc[0].holder, lc.client)
    job.ACK_TIMEOUT = 0.5
    state = job.run([Node(id=n.id, uri=n.uri) for n in lc[0].cluster.nodes]
                    + [Node(id="nodeX", uri=URI(port=10199))])
    assert state == "FAILED"
    assert "nodeX" in job.failed
    assert len(lc[0].cluster.nodes) == 2  # membership unchanged


def test_down_event_fails_pending_ack_immediately():
    """A target that dies after accepting its dispatch must not stall
    the resize for the whole ACK deadline: the failure detector's DOWN
    event fails its pending ACK at once."""
    import threading

    lc = LocalCluster(2)
    seed(lc)

    class AcceptNeverAck:
        def handle_message(self, message):
            pass  # accepted, then "crashed": no ACK ever

    lc.client.register("nodeX", AcceptNeverAck())
    job = ResizeJob(lc[0].cluster, lc[0].holder, lc.client)
    job.ACK_TIMEOUT = 30.0  # deadline is NOT what unblocks us

    def kill_target():
        lc[0].cluster._emit("update", "nodeX", "DOWN")

    t = threading.Timer(0.2, kill_target)
    t.start()
    import time
    start = time.monotonic()
    state = job.run([Node(id=n.id, uri=n.uri) for n in lc[0].cluster.nodes]
                    + [Node(id="nodeX", uri=URI(port=10199))])
    assert state == "FAILED"
    assert time.monotonic() - start < 10.0
    assert "nodeX" in job.failed


def test_holder_cleaner_removes_unowned_fragments():
    """After a grow-resize, the old owner GCs fragments that moved away
    (reference holderCleaner, holder.go:1126): memory fragment gone,
    shard still queryable via its new owner."""
    lc = LocalCluster(2)
    cols = seed(lc, n_shards=12)

    from pilosa_tpu.cluster.cluster import STATE_NORMAL
    from pilosa_tpu.cluster.harness import ClusterNode
    new_member = Node(id="node2", uri=URI(port=10103))
    member_list = [Node(id=n.id, uri=n.uri) for n in lc[0].cluster.nodes]
    c2 = Cluster("node2", member_list + [new_member], replica_n=1,
                 client=lc.client)
    c2.set_state(STATE_NORMAL)
    cn2 = ClusterNode("node2", c2)
    cn2.apply_schema(lc[0].holder.schema())
    lc.client.register("node2", cn2)
    lc.nodes.append(cn2)

    before = {cn.id: {s for v in cn.holder.field("i", "f").views.values()
                      for s in v.available_shards()}
              for cn in lc.nodes[:2]}
    job = ResizeJob(lc[0].cluster, lc[0].holder, lc.client)
    assert job.run([Node(id=n.id, uri=n.uri) for n in lc[0].cluster.nodes]
                   + [new_member]) == "DONE"

    import time
    deadline = time.time() + 5.0
    moved_any = False
    while time.time() < deadline:
        moved_any = False
        ok = True
        for cn in lc.nodes[:2]:
            cl = cn.cluster
            f = cn.holder.field("i", "f")
            local_now = {s for v in f.views.values()
                         for s in v.available_shards()}
            for s in before[cn.id]:
                owned = any(n.id == cn.id for n in cl.shard_nodes("i", s))
                if not owned:
                    moved_any = True
                    if s in local_now:
                        ok = False  # cleaner hasn't run yet
        if ok:
            break
        time.sleep(0.05)
    assert moved_any, "resize moved nothing; test is vacuous"
    assert ok, "old owners still hold fragments for moved shards"
    # Data completeness survives the GC.
    for node in range(3):
        assert lc.query("i", "Count(Row(f=1))", node=node,
                        cache=False) == [len(cols)]


def test_holder_cleaner_prevents_stale_bits_on_reownership():
    """Clear a bit after its shard moved away, then move the shard BACK:
    the original owner must serve the repaired state, not resurrect its
    stale pre-move fragment (the exact failure holderCleaner exists to
    prevent — anti-entropy merges never REMOVE bits)."""
    import time

    lc = LocalCluster(2, replica_n=2)
    lc.create_index("i")
    lc.create_field("i", "f")
    # Pick a shard whose 3-node replica set will DROP one of the two
    # original nodes (deterministic ring math, no luck involved).
    ring3 = Cluster("node0", [Node(id=f"node{i}", uri=URI(port=10101 + i))
                              for i in range(3)], replica_n=2)
    shard = next(s for s in range(64)
                 if {"node0", "node1"} -
                 {n.id for n in ring3.shard_nodes("i", s)})
    x = shard * SHARD_WIDTH + 11
    lc.query("i", f"Set({x}, f=1)")
    assert lc.query("i", "Count(Row(f=1))") == [1]

    # Grow to 3 nodes: some shards' replica sets drop node0 or node1.
    from pilosa_tpu.cluster.cluster import STATE_NORMAL
    from pilosa_tpu.cluster.harness import ClusterNode
    new_member = Node(id="node2", uri=URI(port=10103))
    member_list = [Node(id=n.id, uri=n.uri) for n in lc[0].cluster.nodes]
    c2 = Cluster("node2", member_list + [new_member], replica_n=2,
                 client=lc.client)
    c2.set_state(STATE_NORMAL)
    cn2 = ClusterNode("node2", c2)
    cn2.apply_schema(lc[0].holder.schema())
    lc.client.register("node2", cn2)
    lc.nodes.append(cn2)
    job = ResizeJob(lc[0].cluster, lc[0].holder, lc.client)
    assert job.run([Node(id=n.id, uri=n.uri) for n in lc[0].cluster.nodes]
                   + [new_member]) == "DONE"
    time.sleep(0.2)  # background ACK applies

    cl = lc[0].cluster
    owners = {n.id for n in cl.shard_nodes("i", shard)}
    demoted = {"node0", "node1"} - owners
    assert demoted, "ring math changed; pick logic needs updating"
    loser = demoted.pop()
    # The demoted node's fragment must be GONE (cleaner ran on commit
    # or on the status broadcast).
    lv = lc.client.peers[loser].holder.field("i", "f").views
    assert all(shard not in v.available_shards() for v in lv.values())

    # Clear x on the CURRENT owners (the demoted node doesn't see it).
    lc.query("i", f"Clear({x}, f=1)")
    assert lc.query("i", "Count(Row(f=1))", cache=False) == [0]

    # Shrink back to the original two nodes: shard 3 maps back to the
    # demoted node, which refetches the REPAIRED fragment.
    keep = [Node(id=n.id, uri=n.uri, is_coordinator=n.is_coordinator)
            for n in lc[0].cluster.nodes if n.id != "node2"]
    job2 = ResizeJob(lc[0].cluster, lc[0].holder, lc.client)
    assert job2.run(keep) == "DONE"
    time.sleep(0.2)
    for node in range(2):
        assert lc.query("i", "Count(Row(f=1))", node=node,
                        cache=False) == [0], "stale bit resurrected"


def test_holder_cleaner_deletes_on_disk_files(tmp_path):
    """HTTP + DiskStore: after a join moves shards away, the old
    owner's .snap/.wal files for those shards are unlinked (reference
    holderCleaner's disk GC, holder.go:1170)."""
    import json
    import os
    import time
    import urllib.request
    from pilosa_tpu.server.node import ServerNode

    ports = _free_ports(3)
    addrs = [f"127.0.0.1:{p}" for p in ports]
    dirs = [str(tmp_path / f"n{i}") for i in range(3)]
    nodes = [ServerNode(bind=a, peers=[x for x in addrs[:2] if x != a],
                        replica_n=1, use_planner=False,
                        anti_entropy_interval=0.0, check_nodes_interval=0.0,
                        data_dir=dirs[i])
             for i, a in enumerate(addrs[:2])]
    for n in nodes:
        n.open()
    joiner = None
    try:
        base = nodes[0].address

        def post(path, body):
            r = urllib.request.Request(base + path, data=body.encode(),
                                       method="POST")
            return json.loads(urllib.request.urlopen(r, timeout=10).read()
                              or b"{}")

        post("/index/i", "{}")
        post("/index/i/field/f", "{}")
        cols = [s * SHARD_WIDTH for s in range(10)]
        for c in cols:
            post("/index/i/query", f"Set({c}, f=1)")
        for n in nodes:
            n.store.flush()

        joiner = ServerNode(bind=addrs[2], join=addrs[1],
                            use_planner=False, anti_entropy_interval=0.0,
                            check_nodes_interval=0.0, data_dir=dirs[2])
        joiner.open()
        deadline = time.time() + 15.0
        while time.time() < deadline and len(joiner.cluster.nodes) != 3:
            time.sleep(0.1)
        assert len(joiner.cluster.nodes) == 3

        # Wait for the cleaners, then assert: every shard an original
        # node no longer owns has no .snap/.wal on its disk.
        def stale_files(node):
            out = []
            cl = node.cluster
            for vname in ("standard",):
                vdir = os.path.join(node.data_dir, "i", "f", vname)
                if not os.path.isdir(vdir):
                    continue
                for fn in os.listdir(vdir):
                    shard = int(fn.rsplit(".", 1)[0])
                    if not any(nd.id == node.id
                               for nd in cl.shard_nodes("i", shard)):
                        out.append(fn)
            return out

        deadline = time.time() + 10.0
        while time.time() < deadline:
            leftovers = [f for n in nodes for f in stale_files(n)]
            if not leftovers:
                break
            time.sleep(0.2)
        cl = nodes[0].cluster
        moved = any(
            not any(nd.id == n.id for nd in cl.shard_nodes("i", s))
            for n in nodes for s in range(10))
        assert moved, "join moved no shards off the originals; vacuous"
        assert not leftovers, leftovers
        # Completeness from every node.
        assert post("/index/i/query", "Count(Row(f=1))") == \
            {"results": [len(cols)]}
    finally:
        for n in nodes + ([joiner] if joiner else []):
            try:
                n.close()
            except Exception:
                pass


def test_transitive_membership_discovery():
    """A node that missed a committed topology learns it from ANY live
    peer holding a NEWER version (memberlist push/pull analog): A's view
    lacks C, B carries topology v1 including C, one sweep on A adopts
    it. A STALE peer (older version) can never pollute the ring."""
    lc = LocalCluster(3, replica_n=1)
    a = lc[0]
    # A missed C's join: amputate C and leave A at version 0 while the
    # others committed version 1.
    a.cluster.nodes = [n for n in a.cluster.nodes if n.id != "node2"]
    for cn in lc.nodes[1:]:
        cn.cluster.topology_version = 1
    assert a.cluster.node_by_id("node2") is None
    changed = check_nodes(a.cluster, lc.client)
    assert "node2" in changed
    assert a.cluster.node_by_id("node2") is not None
    assert a.cluster.topology_version == 1
    # Idempotent: next sweep adds nothing.
    assert check_nodes(a.cluster, lc.client) == []


def test_stale_peer_cannot_resurrect_removed_member():
    """The ghost-resurrection hazard: B holds a STALE view (missed a
    shrink) that still lists the removed node2; A (same or newer
    version) must NOT re-adopt it — placement would shift and the
    holder GC would delete live data."""
    lc = LocalCluster(3, replica_n=1)
    a, b = lc[0], lc[1]
    # A committed the shrink at version 2; B is stale at version 1 and
    # still lists node2.
    a.cluster.nodes = [n for n in a.cluster.nodes if n.id != "node2"]
    a.cluster.topology_version = 2
    b.cluster.topology_version = 1
    lc.client.down.add("node2")
    changed = check_nodes(a.cluster, lc.client)
    assert a.cluster.node_by_id("node2") is None, "ghost resurrected"
    assert a.cluster.topology_version == 2
    assert all(c != "node2" for c in changed), \
        "removed ghost must not appear as a liveness transition"


def test_stale_broadcast_cannot_roll_back_topology():
    """The PUSH path enforces the same strictly-newer gate as the pull
    path: a delayed/replayed cluster-status broadcast carrying an OLDER
    committed topology must not roll the ring back (it would resurrect
    removed members and shift jump-hash placement under the holder GC)."""
    from pilosa_tpu.cluster.resize import apply_cluster_status

    lc = LocalCluster(3, replica_n=1)
    a = lc[0]
    ghost_json = [n.to_json() for n in a.cluster.nodes]  # includes node2
    # A committed the shrink at version 2.
    a.cluster.nodes = [n for n in a.cluster.nodes if n.id != "node2"]
    a.cluster.topology_version = 2
    # A delayed broadcast of the PRE-shrink topology (version 1) arrives.
    apply_cluster_status(a.cluster, ghost_json, version=1)
    assert a.cluster.node_by_id("node2") is None, "stale push rolled back"
    assert a.cluster.topology_version == 2
    # Equal version: replay of the current commit is also a no-op.
    apply_cluster_status(a.cluster, ghost_json, version=2)
    assert a.cluster.node_by_id("node2") is None
    # Strictly newer wins: the ring moves forward.
    newer = [n.to_json() for n in a.cluster.nodes]
    apply_cluster_status(a.cluster, newer, version=3)
    assert a.cluster.topology_version == 3


def test_stuck_resizing_peer_self_heals():
    """A node left in RESIZING with no commit broadcast coming (it was
    removed by the shrink, or the coordinator crashed mid-job) reopens
    its gate on the next sweep: the coordinator's view is authoritative,
    and a dead coordinator means the job died with it."""
    from pilosa_tpu.cluster import STATE_NORMAL, STATE_RESIZING
    from pilosa_tpu.cluster.harness import LocalCluster
    from pilosa_tpu.cluster.resize import check_nodes

    # Case 1: coordinator reports the resize is over (removed node).
    lc = LocalCluster(3)
    peer = lc[1]
    peer.cluster.set_state(STATE_RESIZING)
    check_nodes(peer.cluster, lc.client)
    assert peer.cluster.state == STATE_NORMAL

    # Case 2: coordinator still mid-job -> the gate STAYS closed.
    lc2 = LocalCluster(3)
    lc2[0].cluster.set_state(STATE_RESIZING)  # coordinator's own view
    lc2[1].cluster.set_state(STATE_RESIZING)
    check_nodes(lc2[1].cluster, lc2.client)
    assert lc2[1].cluster.state == STATE_RESIZING

    # Case 3: coordinator dead -> the job died with it; the phantom
    # RESIZING clears only after several consecutive DOWN sweeps (a
    # one-sweep blip must NOT reopen the gate mid-resize), then
    # liveness takes over (replica_n=1 with a dead node is STARTING —
    # data genuinely unavailable, honest status).
    from pilosa_tpu.cluster.resize import RESIZING_COORD_DOWN_SWEEPS

    lc3 = LocalCluster(3)
    lc3[1].cluster.set_state(STATE_RESIZING)
    lc3.client.down.add("node0")
    for i in range(RESIZING_COORD_DOWN_SWEEPS - 1):
        check_nodes(lc3[1].cluster, lc3.client)
        assert lc3[1].cluster.state == STATE_RESIZING, f"sweep {i}"
    check_nodes(lc3[1].cluster, lc3.client)
    assert lc3[1].cluster.state == "STARTING"

    # Case 4: the coordinator itself never self-clears mid-job (its
    # ResizeJob owns the transition).
    lc4 = LocalCluster(3)
    lc4[0].cluster.set_state(STATE_RESIZING)
    check_nodes(lc4[0].cluster, lc4.client)
    assert lc4[0].cluster.state == STATE_RESIZING


def test_writes_racing_a_live_join_converge():
    """A client writing through the cluster while a node joins: writes
    refused by the resize gate (HTTP 405) are retried, and after the
    join every accepted write is present — none silently dropped onto a
    ring position the committed topology GC'd."""
    import json
    import threading
    import time
    import urllib.error
    import urllib.request
    from pilosa_tpu.server.node import ServerNode

    ports = _free_ports(3)
    addrs = [f"127.0.0.1:{p}" for p in ports]
    nodes = [ServerNode(bind=a, peers=[x for x in addrs[:2] if x != a],
                        replica_n=1, use_planner=False,
                        anti_entropy_interval=0.0,
                        check_nodes_interval=0.0)
             for a in addrs[:2]]
    for n in nodes:
        n.open()
    joiner = None
    stop = threading.Event()
    accepted: list[int] = []
    errors: list[str] = []

    def writer():
        base = nodes[0].address
        i = 0
        while not stop.is_set():
            col = i * SHARD_WIDTH // 4 + i  # spread over shards
            i += 1
            body = f"Set({col}, f=1)".encode()
            for _attempt in range(60):
                req = urllib.request.Request(base + "/index/i/query",
                                             data=body, method="POST")
                try:
                    urllib.request.urlopen(req, timeout=10).read()
                    accepted.append(col)
                    break
                except urllib.error.HTTPError as e:
                    e.read()
                    if e.code == 405:  # resize gate: retry
                        time.sleep(0.05)
                        continue
                    errors.append(f"HTTP {e.code} for {col}")
                    return
                except Exception as e:  # pragma: no cover
                    errors.append(repr(e))
                    return
            else:
                errors.append(f"write {col} starved past the resize")
                return
            time.sleep(0.01)

    try:
        base = nodes[0].address

        def post(path, body):
            r = urllib.request.Request(base + path, data=body.encode(),
                                       method="POST")
            return json.loads(urllib.request.urlopen(r, timeout=10).read()
                              or b"{}")

        post("/index/i", "{}")
        post("/index/i/field/f", "{}")
        t = threading.Thread(target=writer)
        t.start()
        time.sleep(0.3)  # some writes land pre-join
        joiner = ServerNode(bind=addrs[2], join=addrs[1],
                            use_planner=False, anti_entropy_interval=0.0,
                            check_nodes_interval=0.0)
        joiner.open()
        deadline = time.time() + 15
        while (len(nodes[0].cluster.nodes) < 3
               and time.time() < deadline):
            time.sleep(0.1)
        assert len(nodes[0].cluster.nodes) == 3
        time.sleep(0.5)  # a few post-join writes
        stop.set()
        t.join(timeout=30)
        assert not errors, errors[:3]
        assert accepted, "no writes ever accepted"
        want = len(set(accepted))
        got = post("/index/i/query", "Count(Row(f=1))")
        assert got == {"results": [want]}, (want, got, len(accepted))
    finally:
        stop.set()
        for n in nodes + ([joiner] if joiner else []):
            try:
                n.close()
            except Exception:
                pass


def test_cleaner_never_runs_mid_resize():
    """The holder GC must not compute ownership under a mid-resize ring:
    it would delete fragments a target just streamed in for its
    NEW-ring shards (permanent loss once the old owner leaves)."""
    from pilosa_tpu.cluster import STATE_NORMAL, STATE_RESIZING
    from pilosa_tpu.cluster.cleaner import clean_holder
    from pilosa_tpu.cluster.harness import LocalCluster

    lc = LocalCluster(2)
    seed(lc, n_shards=4)
    b = lc[1]
    # Give B a fragment it does NOT own so the cleaner would bite.
    unowned = [s for s in range(4)
               if not any(n.id == "node1"
                          for n in b.cluster.shard_nodes("i", s))]
    assert unowned
    v = b.holder.index("i").field("f").create_view_if_not_exists("standard")
    v.create_fragment_if_not_exists(unowned[0]).set_bit(1, 5)

    b.cluster.set_state(STATE_RESIZING)
    assert clean_holder(b.holder, b.cluster) == 0, \
        "cleaner ran under a mid-resize ring"
    assert v.fragment(unowned[0]) is not None
    b.cluster.set_state(STATE_NORMAL)
    assert clean_holder(b.holder, b.cluster) >= 1
    assert v.fragment(unowned[0]) is None


def test_topology_version_survives_restart(tmp_path):
    """The committed ring + version persist (reference .topology file):
    a restarted coordinator must not reset to version 0 — its next
    commit would broadcast a version every peer rejects as stale,
    forking the cluster."""
    import json
    import os

    from pilosa_tpu.server.node import ServerNode

    ports = _free_ports(2)
    addrs = [f"127.0.0.1:{p}" for p in ports]
    d0 = str(tmp_path / "n0")
    n0 = ServerNode(bind=addrs[0], peers=[addrs[1]], data_dir=d0,
                    use_planner=False, anti_entropy_interval=0.0,
                    check_nodes_interval=0.0)
    n0.open()
    try:
        n0.cluster.topology_version = 7
        n0.cluster.replica_n = 2  # adopted from a broadcast, say
        n0.cluster.notify_topology()
        doc = json.load(open(os.path.join(d0, "topology.json")))
        assert doc["version"] == 7 and doc["replicaN"] == 2
        # Monotonic guard: a straggling saver holding an OLDER snapshot
        # must not win the replace.
        n0.cluster.topology_version = 5
        n0.cluster.notify_topology()
        assert json.load(open(os.path.join(d0, "topology.json")))[
            "version"] == 7
        n0.cluster.topology_version = 7
    finally:
        n0.close()

    reborn = ServerNode(bind=addrs[0], peers=[addrs[1]], data_dir=d0,
                        use_planner=False, anti_entropy_interval=0.0,
                        check_nodes_interval=0.0)
    reborn.open()
    try:
        assert reborn.cluster.topology_version == 7
        assert reborn.cluster.replica_n == 2
    finally:
        reborn.close()


def test_removed_state_cleared_by_newer_ring_including_node():
    """A REMOVED node re-added by a NEWER committed topology exits the
    terminal state (operator re-add flow; round-5 REMOVED semantics)."""
    from pilosa_tpu.cluster.cluster import (
        STATE_NORMAL, STATE_REMOVED, Cluster,
    )
    from pilosa_tpu.cluster.node import Node, URI
    from pilosa_tpu.cluster.resize import apply_cluster_status

    nodes = [Node(id=f"n{i}", uri=URI(host="h", port=1 + i),
                  is_coordinator=(i == 0)) for i in range(3)]
    c = Cluster(local_id="n2", nodes=[Node(id=n.id, uri=n.uri,
                                           is_coordinator=n.is_coordinator)
                                      for n in nodes])
    c.set_state(STATE_NORMAL)

    # Commit v1 excludes n2: terminal REMOVED, gate logic elsewhere.
    apply_cluster_status(c, [n.to_json() for n in nodes[:2]], version=1)
    assert c.state == STATE_REMOVED
    # A STALE broadcast can't resurrect us...
    apply_cluster_status(c, [n.to_json() for n in nodes], version=1)
    assert c.state == STATE_REMOVED
    # ...but a NEWER committed ring that includes us ends the exile.
    apply_cluster_status(c, [n.to_json() for n in nodes], version=2)
    assert c.state == STATE_NORMAL
    assert any(n.id == "n2" for n in c.nodes)


@pytest.mark.slow
def test_stateless_ex_coordinator_rejoin_hands_over_flag(tmp_path):
    """The leaderless wedge the chaos soak found: the flagged
    coordinator's process restarts without cluster state and announces
    as a joiner — peers must hand the flag to a live survivor and admit
    it instead of forwarding its own announce back to it forever."""
    import json
    import time
    import urllib.request

    from pilosa_tpu.server.node import ServerNode

    addrs = [f"127.0.0.1:{p}" for p in _free_ports(3)]
    # Boot ring: the flag lands on the sorted-first address.
    coord_addr = sorted(addrs)[0]
    nodes = {a: ServerNode(bind=a, peers=[x for x in addrs if x != a],
                           replica_n=2, use_planner=False,
                           check_nodes_interval=0.5,
                           anti_entropy_interval=1.0)
             for a in addrs}
    for n in nodes.values():
        n.open()
    try:
        survivor = next(a for a in addrs if a != coord_addr)
        # Kill the coordinator, then bring it back STATELESS (fresh
        # dir, join via a survivor).
        nodes[coord_addr].close()
        nodes[coord_addr] = ServerNode(
            bind=coord_addr, join=survivor,
            data_dir=str(tmp_path / "reborn"), use_planner=False,
            check_nodes_interval=0.5, anti_entropy_interval=1.0)
        nodes[coord_addr].open()

        deadline = time.time() + 90
        ok = False
        while time.time() < deadline:
            try:
                sts = {a: json.loads(urllib.request.urlopen(
                    f"http://{a}/status", timeout=5).read())
                    for a in addrs}
                rings_full = all(len(s["nodes"]) == 3
                                 for s in sts.values())
                flags = {a: [n["id"] for n in s["nodes"]
                             if n.get("isCoordinator")]
                         for a, s in sts.items()}
                one_flag = all(len(f) == 1 for f in flags.values())
                # The handover moved the flag OFF the stateless
                # rejoiner onto a survivor, consistently everywhere.
                if (rings_full and one_flag
                        and len({f[0] for f in flags.values()}) == 1
                        and flags[survivor][0] != coord_addr):
                    ok = True
                    break
            except Exception:
                pass
            time.sleep(0.5)
        assert ok, "stateless ex-coordinator never re-admitted with handover"
    finally:
        for n in nodes.values():
            try:
                n.close()
            except Exception:
                pass


# ---------------------------------------------------------------------------
# Serve-through resize (zero-downtime elasticity): the ring answers reads
# and writes for the whole job; writes on in-flight shards dual-apply to
# old and future owners; per-shard cutover happens only after the target
# holds a complete epoch-current copy; aborted/killed streams leave the
# old ring authoritative and a re-run resumes from the applied prefix.
# ---------------------------------------------------------------------------


def _boot_joiner(lc: LocalCluster, node_id=None, port=None) -> Node:
    """Register a fresh empty member on the shared transport (operator
    booted a process with --join); returns its ring Node."""
    from pilosa_tpu.cluster.cluster import STATE_STARTING
    from pilosa_tpu.cluster.harness import ClusterNode
    if node_id is None:
        node_id = f"node{len(lc.nodes)}"
    if port is None:
        port = 10130 + len(lc.nodes)
    member = Node(id=node_id, uri=URI(port=port))
    ring = [Node(id=n.id, uri=n.uri) for n in lc[0].cluster.nodes]
    c = Cluster(node_id, ring + [member],
                replica_n=lc[0].cluster.replica_n, client=lc.client)
    c.set_state(STATE_STARTING)
    cn = ClusterNode(node_id, c)
    cn.apply_schema(lc[0].holder.schema())
    lc.client.register(node_id, cn)
    lc.nodes.append(cn)
    return member


def _old_ring(lc: LocalCluster) -> list[Node]:
    return [Node(id=n.id, uri=n.uri) for n in lc[0].cluster.nodes]


def test_serve_through_resize_reads_and_writes(monkeypatch):
    """Mid-migration (first PTS1 push in flight) the ring still answers
    queries under the old placement and dual-applies writes; the
    mid-stream write survives the cutover onto the new ring."""
    from pilosa_tpu.cluster.client import LocalClient
    from pilosa_tpu.obs.stats import MemoryStats
    lc = LocalCluster(2)
    cols = seed(lc)
    stats = MemoryStats()
    member = _boot_joiner(lc)
    # One shared sink: bytesStreamed counts on the source, cutover and
    # shardsMigrated on the target, dualWrites on the write coordinator.
    for cn in lc.nodes:
        cn.cluster.stats = stats
    orig = LocalClient.send_import_stream
    mid = []

    def spy(self, node, reqs, chunked=False, qos_class=None):
        reqs = list(reqs)
        if not mid:
            sh = reqs[0]["shard"]
            # Read served under the OLD placement while the copy is
            # mid-flight, with no resize gate in the way.
            mid.append(lc.query("i", "Count(Row(f=1))", cache=False))
            # Write into the shard being streamed RIGHT NOW: it must
            # dual-apply (old owner + future owner) and survive cutover.
            lc.query("i", f"Set({sh * SHARD_WIDTH + 123}, f=1)")
            mig = lc[0].cluster.migration
            assert mig is not None
            # /debug/resize halves, live mid-stream: the job is RUNNING
            # with this shard in flight and the table names the new ring.
            snap = job.snapshot()
            assert snap["state"] == "RUNNING"
            assert snap["shards"]["inFlight"] >= 1
            msnap = mig.snapshot()
            assert member.id in msnap["newNodes"]
            assert msnap["job"] == snap["job"]
        return orig(self, node, reqs, chunked=chunked, qos_class=qos_class)

    monkeypatch.setattr(LocalClient, "send_import_stream", spy)
    job = ResizeJob(lc[0].cluster, lc[0].holder, lc.client)
    assert job.run(_old_ring(lc) + [member]) == "DONE"
    assert mid == [[len(cols)]]                     # served mid-stream
    for node in range(3):
        assert lc.query("i", "Count(Row(f=1))", node=node,
                        cache=False) == [len(cols) + 1]
    # Telemetry: the job surfaced its progress counters.
    assert stats.counter_value("cluster.resize.shardsMigrated") >= 1
    assert stats.counter_value("cluster.resize.bytesStreamed") > 0
    assert stats.timing_count("cluster.resize.cutover") >= 1


def _fatten_shard(lc: LocalCluster, shard: int, n_bits: int, seed_: int,
                  row: int = 0):
    rng_ = np.random.default_rng(seed_)
    rows = np.full(n_bits, row, dtype=np.uint64)
    cols = (rng_.integers(0, SHARD_WIDTH, n_bits).astype(np.uint64)
            + np.uint64(shard * SHARD_WIDTH))
    owner = lc[0].cluster.shard_nodes("i", shard)[0]
    lc.client.peers[owner.id].handle_import_request("i", "f",
                                                    rows=rows, cols=cols)
    return owner


def _moved_shard(lc: LocalCluster, member: Node, n_shards: int = 6) -> int:
    """A shard whose primary owner under the grown ring is the joiner."""
    new_view = Cluster("x", _old_ring(lc) + [member],
                       replica_n=lc[0].cluster.replica_n)
    for s in range(n_shards):
        if new_view.shard_nodes("i", s)[0].id == member.id:
            return s
    raise AssertionError("no shard moves to the joiner")


def test_abort_mid_stream_leaves_ring_routable_then_resume(monkeypatch):
    """ResizeJob.abort mid-PTS1-stream: the partially-migrated shard
    stays routable (old owner authoritative), every member drops its
    migration table, and a later re-run converges to DONE."""
    from pilosa_tpu.core.fragment import Fragment
    from pilosa_tpu.cluster.client import LocalClient
    monkeypatch.setattr(Fragment, "TRANSFER_CHUNK_BITS", 2048)
    lc = LocalCluster(2)
    cols = seed(lc)
    member = _boot_joiner(lc)
    big = _moved_shard(lc, member)
    _fatten_shard(lc, big, 12_000, seed_=1)
    expect = lc.query("i", "Count(Row(f=0))", cache=False)
    job = ResizeJob(lc[0].cluster, lc[0].holder, lc.client)
    orig = LocalClient.send_import_stream
    torn = []

    def spy(self, node, reqs, chunked=False, qos_class=None):
        reqs = list(reqs)
        if not torn:
            torn.append(node.id)
            n = max(1, len(reqs) // 2)
            orig(self, node, reqs[:n], chunked=chunked, qos_class=qos_class)
            job.abort()
            raise ConnectionError("stream torn down by abort")
        return orig(self, node, reqs, chunked=chunked, qos_class=qos_class)

    monkeypatch.setattr(LocalClient, "send_import_stream", spy)
    assert job.run(_old_ring(lc) + [member]) == "ABORTED"
    # Old ring authoritative and fully routable; tables dropped ring-wide.
    assert len(lc[0].cluster.nodes) == 2
    assert all(cn.cluster.migration is None for cn in lc.nodes)
    for node in range(2):
        assert lc.query("i", "Count(Row(f=1))", node=node,
                        cache=False) == [len(cols)]
        assert lc.query("i", "Count(Row(f=0))", node=node,
                        cache=False) == expect
    # Resume: a fresh job re-streams (sets are idempotent — the applied
    # prefix on the target is simply re-covered) and commits.
    job2 = ResizeJob(lc[0].cluster, lc[0].holder, lc.client)
    assert job2.run(_old_ring(lc) + [member]) == "DONE"
    assert len(lc[0].cluster.nodes) == 3
    for node in range(3):
        assert lc.query("i", "Count(Row(f=1))", node=node,
                        cache=False) == [len(cols)]
        assert lc.query("i", "Count(Row(f=0))", node=node,
                        cache=False) == expect


def test_kill_target_mid_shard_then_resume(monkeypatch):
    """Target dies mid-shard: the job FAILS (old topology intact, ring
    keeps serving), the target retains the applied prefix, and a re-run
    resumes over PTS1 to a bit-identical copy."""
    from pilosa_tpu.core.fragment import Fragment
    from pilosa_tpu.cluster.client import LocalClient
    monkeypatch.setattr(Fragment, "TRANSFER_CHUNK_BITS", 2048)
    lc = LocalCluster(2)
    cols = seed(lc)
    member = _boot_joiner(lc)
    big = _moved_shard(lc, member)
    owner = _fatten_shard(lc, big, 12_000, seed_=2)
    src_frag = lc.client.peers[owner.id].holder.fragment(
        "i", "f", "standard", big)
    total = src_frag.bit_count()
    job = ResizeJob(lc[0].cluster, lc[0].holder, lc.client)
    orig = LocalClient.send_import_stream
    killed = []

    def spy(self, node, reqs, chunked=False, qos_class=None):
        reqs = list(reqs)
        if not killed and any(r["shard"] == big and r["field"] == "f"
                              for r in reqs):
            killed.append(node.id)
            keep = [r for r in reqs
                    if r["shard"] == big and r["field"] == "f"]
            n = max(1, len(keep) // 2)
            orig(self, node, keep[:n], chunked=chunked, qos_class=qos_class)
            raise ConnectionError("target killed mid-shard")
        return orig(self, node, reqs, chunked=chunked, qos_class=qos_class)

    monkeypatch.setattr(LocalClient, "send_import_stream", spy)
    assert job.run(_old_ring(lc) + [member]) == "FAILED"
    assert killed == [member.id]
    # Applied prefix survives on the target: strictly partial copy.
    part = lc.client.peers[member.id].holder.fragment(
        "i", "f", "standard", big)
    assert part is not None and 0 < part.bit_count() < total
    # Ring serves throughout, from the old placement.
    assert len(lc[0].cluster.nodes) == 2
    for node in range(2):
        assert lc.query("i", "Count(Row(f=1))", node=node,
                        cache=False) == [len(cols)]
    # Resume: the re-run streams the remainder (idempotent sets over the
    # prefix) and the final copy is bit-identical to the source.
    job2 = ResizeJob(lc[0].cluster, lc[0].holder, lc.client)
    assert job2.run(_old_ring(lc) + [member]) == "DONE"
    got = lc.client.peers[member.id].holder.fragment(
        "i", "f", "standard", big)
    assert got is not None and got.bit_count() == total
    assert got.checksum_blocks() == src_frag.checksum_blocks()


@pytest.mark.parametrize("gen_seed", [7, 77, 777])
def test_generative_dual_ownership_equivalence(monkeypatch, gen_seed):
    """Random Set/Clear/import interleaved with every stage of a grow
    resize must leave the elastic ring bit-identical to a no-resize
    control ring fed the same operations (no lost writes, no
    resurrected bits across the dual-ownership window)."""
    from pilosa_tpu.cluster.client import LocalClient
    rng_ = np.random.default_rng(gen_seed)
    lc = LocalCluster(2)
    ctl = LocalCluster(2)
    for ring in (lc, ctl):
        ring.create_index("i")
        ring.create_field("i", "f")
    n_rows, n_shards = 3, 4
    col_space = n_shards * SHARD_WIDTH

    def routed_import(ring, rows, cols):
        by: dict[int, tuple[list, list]] = {}
        for r, c in zip(rows, cols):
            rs, cs = by.setdefault(int(c) // SHARD_WIDTH, ([], []))
            rs.append(int(r))
            cs.append(int(c))
        cl = ring[0].cluster
        for sh, (rs, cs) in by.items():
            # Owner legs FIRST, dual legs after — the same ordering the
            # server's import router uses (the catch-up epoch guard
            # depends on it).
            old_ids = [n.id for n in cl.shard_nodes("i", sh)]
            mig = cl.migration
            dual = ([n.id for n in mig.dual_targets(cl, "i", sh)
                     if n.id not in old_ids] if mig is not None else [])
            for nid in old_ids + dual:
                ring.client.peers[nid].handle_import_request(
                    "i", "f", rows=rs, cols=cs)

    def batch(k=10):
        for _ in range(k):
            kind = int(rng_.integers(0, 3))
            if kind == 2:
                n = int(rng_.integers(1, 30))
                rs = rng_.integers(0, n_rows, n)
                cs = rng_.integers(0, col_space, n)
                for ring in (lc, ctl):
                    routed_import(ring, rs, cs)
                continue
            r = int(rng_.integers(0, n_rows))
            c = int(rng_.integers(0, col_space))
            op = "Set" if kind == 0 else "Clear"
            for ring in (lc, ctl):
                ring.query("i", f"{op}({c}, f={r})")

    batch(30)
    member = _boot_joiner(lc)
    orig = LocalClient.send_import_stream

    def spy(self, node, reqs, chunked=False, qos_class=None):
        reqs = list(reqs)
        batch(4)   # races the bulk copy's snapshot
        out = orig(self, node, reqs, chunked=chunked, qos_class=qos_class)
        batch(4)   # lands in the catch-up window, pre-cutover
        return out

    monkeypatch.setattr(LocalClient, "send_import_stream", spy)
    job = ResizeJob(lc[0].cluster, lc[0].holder, lc.client)
    assert job.run(_old_ring(lc) + [member]) == "DONE"
    monkeypatch.setattr(LocalClient, "send_import_stream", orig)
    batch(15)      # post-commit traffic on the grown ring
    for r in range(n_rows):
        want = ctl.query("i", f"Row(f={r})",
                         cache=False)[0].columns().tolist()
        for node in range(len(lc.nodes)):
            got = lc.query("i", f"Row(f={r})", node=node,
                           cache=False)[0].columns().tolist()
            assert got == want, (gen_seed, r, node)


@pytest.mark.slow
def test_elastic_soak_grow_shrink_under_fire():
    """Soak drill: a node is ADDED and then a different node REMOVED
    while a query storm and a background PTS1 ingest keep running.
    Asserts zero failed queries, zero lost or resurrected bits
    (oracle scrub + cross-replica checksum agreement), and a
    resize-window p99 bounded against the steady-state p99."""
    import threading
    import time as _time
    from pilosa_tpu.obs.stats import MemoryStats

    lc = LocalCluster(3, replica_n=2)
    lc.create_index("i")
    lc.create_field("i", "f")
    stats = MemoryStats()
    for cn in lc.nodes:
        cn.cluster.stats = stats
    n_rows, n_shards = 2, 4
    col_space = n_shards * SHARD_WIDTH

    # Seed enough bulk that the migration streams take real time (the
    # fire window the storm must survive).
    seed_rng = np.random.default_rng(3)
    seed_rows = seed_rng.integers(0, n_rows, 30_000).astype(np.uint64)
    seed_cols = seed_rng.integers(0, col_space, 30_000).astype(np.uint64)
    oracle: set[tuple[int, int]] = set()

    def pts1_send(rows_b, cols_b):
        """Route one import batch the way the server's import router
        does: current owners first, then the migration table's dual
        targets; re-send (idempotent) if the topology committed under
        us mid-batch."""
        cl = lc[0].cluster
        for _attempt in range(4):
            v0 = cl.topology_version
            by: dict[int, tuple[list, list]] = {}
            for r, c in zip(rows_b, cols_b):
                rs, cs = by.setdefault(int(c) // SHARD_WIDTH, ([], []))
                rs.append(int(r))
                cs.append(int(c))
            for sh, (rs, cs) in by.items():
                mig = cl.migration
                owners = list(cl.shard_nodes("i", sh))
                seen = {o.id for o in owners}
                dual = ([n for n in mig.dual_targets(cl, "i", sh)
                         if n.id not in seen] if mig is not None else [])
                reqs = [{"index": "i", "field": "f",
                         "rowIDs": rs, "columnIDs": cs}]
                for n in owners + dual:
                    lc.client.send_import_stream(n, reqs,
                                                 qos_class="batch")
            if cl.topology_version == v0:
                return
        raise AssertionError("topology kept moving across 4 resends")

    pts1_send(seed_rows, seed_cols)
    oracle.update(zip(seed_rows.tolist(), seed_cols.tolist()))

    stop = threading.Event()
    failures: list[str] = []
    phase = ["steady"]

    def storm():
        qrng = np.random.default_rng(5)
        # node0 and node1 are members for the whole drill (node3 joins,
        # node2 leaves) — query both so reads cross the wire.
        while not stop.is_set():
            r = int(qrng.integers(0, n_rows))
            node = int(qrng.integers(0, 2))
            t0 = _time.monotonic()
            try:
                out = lc.query("i", f"Count(Row(f={r}))", node=node,
                               cache=False)
                assert isinstance(out[0], int)
            except Exception as e:  # noqa: BLE001 - any failure = drill fail
                failures.append(repr(e))
            stats.timing(f"elastic.query.{phase[0]}",
                         _time.monotonic() - t0)

    def ingest():
        irng = np.random.default_rng(9)
        while not stop.is_set():
            kind = int(irng.integers(0, 4))
            try:
                if kind == 3 and oracle:
                    # Clear a bit this thread set earlier: exercises the
                    # no-resurrection half of the dual-apply contract.
                    r, c = sorted(oracle)[int(irng.integers(0, len(oracle)))]
                    lc.query("i", f"Clear({c}, f={r})")
                    oracle.discard((r, c))
                elif kind == 2:
                    r = int(irng.integers(0, n_rows))
                    c = int(irng.integers(0, col_space))
                    lc.query("i", f"Set({c}, f={r})")
                    oracle.add((r, c))
                else:
                    n = int(irng.integers(20, 200))
                    rs = irng.integers(0, n_rows, n)
                    cs = irng.integers(0, col_space, n)
                    pts1_send(rs, cs)
                    oracle.update(zip(rs.tolist(), cs.tolist()))
            except Exception as e:  # noqa: BLE001
                failures.append("ingest: " + repr(e))
            _time.sleep(0.005)

    threads = [threading.Thread(target=storm, daemon=True),
               threading.Thread(target=ingest, daemon=True)]
    for t in threads:
        t.start()
    try:
        _time.sleep(1.2)                 # steady-state timing baseline
        phase[0] = "fire"
        grown = lc.add_node()            # grow under fire
        for cn in lc.nodes:
            cn.cluster.stats = stats
        _time.sleep(0.5)
        lc.remove_node("node2")          # shrink under fire
        _time.sleep(0.5)
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=30)
    assert not any(t.is_alive() for t in threads)
    assert failures == [], failures[:5]
    assert {cn.id for cn in lc.nodes} == {"node0", "node1", grown.id}

    # p99 during the resize window bounded vs steady state (floor
    # absorbs scheduler noise on tiny absolute latencies).
    assert stats.timing_count("elastic.query.steady") > 0
    assert stats.timing_count("elastic.query.fire") > 0
    steady = stats.timing_quantile("elastic.query.steady", 0.99)
    fire = stats.timing_quantile("elastic.query.fire", 0.99)
    assert fire <= 3 * max(steady, 0.05), (steady, fire)

    # Scrub-verify: exact oracle state on every member, from every
    # coordinator (no lost writes, no resurrected bits)...
    for r in range(n_rows):
        want = sorted(c for rr, c in oracle if rr == r)
        for node in range(len(lc.nodes)):
            got = lc.query("i", f"Row(f={r})", node=node,
                           cache=False)[0].columns().tolist()
            assert got == want, (r, lc.nodes[node].id,
                                 len(got), len(want))
    # ...and bit-identical replicas (checksum agreement shard by shard).
    cl = lc[0].cluster
    for sh in range(n_shards):
        sums = {}
        for n in cl.shard_nodes("i", sh):
            frag = lc.client.peers[n.id].holder.fragment(
                "i", "f", "standard", sh)
            sums[n.id] = frag.checksum_blocks() if frag is not None else {}
        assert len({tuple(sorted(s.items())) for s in sums.values()}) == 1, \
            (sh, sorted(sums))
