"""Elastic resize + failure-detector tests.

Models cluster_internal_test.go's fragSources cases and the clustertests
node add/remove flows.
"""

import numpy as np
import pytest

from pilosa_tpu.cluster import Cluster, Node
from pilosa_tpu.cluster.harness import LocalCluster
from pilosa_tpu.cluster.node import URI
from pilosa_tpu.cluster.resize import (
    ResizeJob,
    check_nodes,
    fragment_sources,
)
from pilosa_tpu.config import SHARD_WIDTH


def test_fragment_sources_pure():
    old = Cluster("a", [Node(id="a"), Node(id="b")], replica_n=1)
    new = Cluster("a", [Node(id="a"), Node(id="b"), Node(id="c")], replica_n=1)
    frags = [("i", "f", "standard", s) for s in range(20)]
    srcs = fragment_sources(old, new, frags)
    # only node c (the new node) fetches anything, and only shards it now owns
    assert set(srcs) <= {"c"}
    for s in srcs.get("c", []):
        assert new.shard_nodes("i", s.shard)[0].id == "c"
        assert s.source_node in ("a", "b")


def seed(lc: LocalCluster, n_shards=6):
    lc.create_index("i")
    lc.create_field("i", "f")
    cols = [s * SHARD_WIDTH + s for s in range(n_shards)]
    for c in cols:
        lc.query("i", f"Set({c}, f=1)")
    return cols


def test_grow_cluster_in_process():
    lc = LocalCluster(2)
    cols = seed(lc)
    assert lc.query("i", "Count(Row(f=1))") == [len(cols)]

    # Boot a third node and join it.
    from pilosa_tpu.cluster.harness import ClusterNode
    from pilosa_tpu.cluster.cluster import STATE_NORMAL
    new_member = Node(id="node2", uri=URI(port=10103))
    member_list = [Node(id=n.id, uri=n.uri) for n in lc[0].cluster.nodes]
    c2 = Cluster("node2", member_list + [new_member], replica_n=1,
                 client=lc.client)
    c2.set_state(STATE_NORMAL)
    cn2 = ClusterNode("node2", c2)
    cn2.apply_schema(lc[0].holder.schema())
    lc.client.register("node2", cn2)
    lc.nodes.append(cn2)

    job = ResizeJob(lc[0].cluster, lc[0].holder, lc.client)
    state = job.run([Node(id=n.id, uri=n.uri) for n in lc[0].cluster.nodes]
                    + [new_member])
    assert state == "DONE"
    assert len(lc[0].cluster.nodes) == 3
    # All data still reachable, from any coordinator.
    for node in range(3):
        assert lc.query("i", "Count(Row(f=1))", node=node) == [len(cols)]


def test_shrink_cluster_in_process():
    lc = LocalCluster(3, replica_n=2)
    cols = seed(lc)
    victim = "node2"
    keep = [Node(id=n.id, uri=n.uri, is_coordinator=n.is_coordinator)
            for n in lc[0].cluster.nodes if n.id != victim]
    job = ResizeJob(lc[0].cluster, lc[0].holder, lc.client)
    assert job.run(keep) == "DONE"
    lc.client.down.add(victim)  # victim actually gone
    for node in range(2):
        assert lc.query("i", "Count(Row(f=1))", node=node) == [len(cols)]


def test_resize_abort():
    lc = LocalCluster(2)
    seed(lc)
    job = ResizeJob(lc[0].cluster, lc[0].holder, lc.client)
    job.abort()
    state = job.run([Node(id=n.id, uri=n.uri) for n in lc[0].cluster.nodes]
                    + [Node(id="nodeX", uri=URI(port=10199))])
    assert state == "ABORTED"
    assert len(lc[0].cluster.nodes) == 2  # membership unchanged


def test_check_nodes_failure_detector():
    lc = LocalCluster(3, replica_n=2)
    c0 = lc[0].cluster
    assert check_nodes(c0, lc.client) == []
    lc.client.down.add("node1")
    changed = check_nodes(c0, lc.client)
    assert changed == ["node1"]
    assert c0.node_by_id("node1").state == "DOWN"
    assert c0.state == "DEGRADED"
    lc.client.down.discard("node1")
    assert check_nodes(c0, lc.client) == ["node1"]
    assert c0.state == "NORMAL"


def test_http_resize_remove_node():
    """Full HTTP flow: 3 servers, coordinator removes one via the REST
    resize route, data remains queryable."""
    import json
    import socket
    import urllib.request
    from pilosa_tpu.server.node import ServerNode

    ports = []
    for _ in range(3):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        ports.append(s.getsockname()[1])
        s.close()
    addrs = [f"127.0.0.1:{p}" for p in ports]
    nodes = [ServerNode(bind=a, peers=[x for x in addrs if x != a],
                        replica_n=2, use_planner=False) for a in addrs]
    for n in nodes:
        n.open()
    try:
        base = nodes[0].address

        def post(path, body):
            r = urllib.request.Request(base + path, data=body.encode(),
                                       method="POST")
            return json.loads(urllib.request.urlopen(r, timeout=10).read()
                              or b"{}")

        post("/index/i", "{}")
        post("/index/i/field/f", "{}")
        cols = [s * SHARD_WIDTH for s in range(5)]
        for c in cols:
            post("/index/i/query", f"Set({c}, f=1)")
        assert post("/index/i/query", "Count(Row(f=1))") == \
            {"results": [len(cols)]}

        # Never remove the node we keep querying (addrs[0]): with random
        # ephemeral ports, sorted(addrs)[-1] is addrs[0] ~1/3 of the time.
        victim = sorted(a for a in addrs if a != addrs[0])[-1]
        post("/cluster/resize/remove-node", json.dumps({"id": victim}))
        st = json.loads(urllib.request.urlopen(base + "/status",
                                               timeout=10).read())
        assert len(st["nodes"]) == 2
        nodes[[i for i, a in enumerate(addrs) if a == victim][0]].close()
        assert post("/index/i/query", "Count(Row(f=1))") == \
            {"results": [len(cols)]}
    finally:
        for n in nodes:
            try:
                n.close()
            except Exception:
                pass
