"""Key translation at device speed (ISSUE 20): device key planes vs the
host-oracle store, snapshot concurrency, version-bump rebuilds, and the
replica-local read path.

The bit-equivalence half mirrors test_generative.py's model-based stress:
the same logical bit set lives in a keyed index (string keys routed
through the full translation path) and an unkeyed oracle index (raw
ids); every random Row/Intersect/Union/Count/TopN tree must agree under
relabeling, with the device plane path forced on AND forced off.
"""

import threading

import numpy as np
import pytest

from pilosa_tpu.core import Holder
from pilosa_tpu.core.field import FieldOptions
from pilosa_tpu.core.index import IndexOptions
from pilosa_tpu.core.translate import TranslateStore
from pilosa_tpu.exec import Executor
from pilosa_tpu.exec import keyplane as kp
from pilosa_tpu.parallel import MeshPlanner, make_mesh

ROWS = [1, 2, 3, 4]


def _row_key(r: int) -> str:
    return f"r{r}"


def _col_key(c: int) -> str:
    return f"c{c}"


def _build_pair(rng, n_bits=160, n_cols=500):
    """One logical bit set, twice: keyed index (keys pre-translated in a
    single batch, bits imported under the allocated ids) and an id
    oracle index (raw ids). Returns (holder, keyed_exec, oracle_exec,
    col_fwd) where col_fwd maps column key -> keyed column id."""
    h = Holder()
    kidx = h.create_index("kt", IndexOptions(keys=True))
    kf = kidx.create_field("f", FieldOptions(keys=True))
    oidx = h.create_index("ot")
    of = oidx.create_field("f")

    rows = rng.choice(ROWS, n_bits)
    cols = rng.integers(0, n_cols, n_bits)

    # Batched allocation up front — also the satellite (a) path: one
    # translate_keys call per store, one lock, one epoch bump.
    row_ids = kf.translate_store.translate_keys(
        [_row_key(r) for r in ROWS])
    row_map = dict(zip(ROWS, row_ids))
    distinct_cols = sorted(set(cols.tolist()))
    col_ids = kidx.translate_store.translate_keys(
        [_col_key(c) for c in distinct_cols])
    col_map = dict(zip(distinct_cols, col_ids))

    kf.import_bits(
        np.array([row_map[r] for r in rows.tolist()], dtype=np.uint64),
        np.array([col_map[c] for c in cols.tolist()], dtype=np.uint64))
    of.import_bits(rows.astype(np.uint64), cols.astype(np.uint64))

    planner = MeshPlanner(h, make_mesh())
    ex = Executor(h, planner=planner)
    return h, ex, planner


def _gen_tree(rng, depth):
    """(keyed_pql, oracle_pql) pair over Row/Intersect/Union."""
    if depth == 0 or rng.random() < 0.4:
        r = ROWS[rng.integers(0, len(ROWS))]
        return f'Row(f="{_row_key(r)}")', f"Row(f={r})"
    op = ["Intersect", "Union"][rng.integers(0, 2)]
    subs = [_gen_tree(rng, depth - 1) for _ in range(2 + int(rng.integers(0, 2)))]
    return (f"{op}({', '.join(s[0] for s in subs)})",
            f"{op}({', '.join(s[1] for s in subs)})")


def _pairs_as_keys(pairs):
    """TopN pairs -> sorted multiset of (key, count); order between
    equal counts is id-order, which differs between labelings."""
    return sorted((p.key, p.count) for p in pairs)


@pytest.mark.parametrize("seed", [5, 17, 41])
def test_keyed_vs_id_bit_equivalence(seed, monkeypatch):
    """Random Row/Intersect/Count trees + TopN agree between the keyed
    index and the id oracle, with the device plane path forced ON (every
    batch probes the plane) and forced OFF (pure host snapshot path)."""
    rng = np.random.default_rng(seed)
    h, ex, planner = _build_pair(rng)
    trees = [_gen_tree(rng, depth=2 + int(rng.integers(0, 2)))
             for _ in range(25)]

    def run(mode):
        monkeypatch.setenv("PILOSA_TPU_TRANSLATE_PLANES", mode)
        counts, rowsets = [], []
        for kq, oq in trees:
            (want,) = ex.execute("ot", f"Count({oq})", cache=False)
            (got,) = ex.execute("kt", f"Count({kq})", cache=False)
            assert got == want, (mode, kq, got, want)
            counts.append(got)
        # Row columns under relabeling: keyed result keys == oracle
        # columns mapped through the column-key naming.
        for kq, oq in trees[:6]:
            (krow,) = ex.execute("kt", kq, cache=False)
            (orow,) = ex.execute("ot", oq, cache=False)
            want_keys = {_col_key(int(c)) for c in orow.columns()}
            assert set(krow.keys) == want_keys, (mode, kq)
            rowsets.append(sorted(krow.keys))
        # TopN: same (key, count) multiset; keyed pairs carry .key via
        # the batched reverse translation.
        (kpairs,) = ex.execute("kt", "TopN(f)", cache=False)
        (opairs,) = ex.execute("ot", "TopN(f)", cache=False)
        top = sorted((_row_key(p.id), p.count) for p in opairs)
        assert _pairs_as_keys(kpairs) == top, mode
        # TopN with a keyed src filter.
        (kpairs,) = ex.execute(
            "kt", f'TopN(f, Row(f="{_row_key(ROWS[0])}"))', cache=False)
        (opairs,) = ex.execute(
            "ot", f"TopN(f, Row(f={ROWS[0]}))", cache=False)
        assert _pairs_as_keys(kpairs) == \
            sorted((_row_key(p.id), p.count) for p in opairs), mode
        return counts, rowsets

    on = run("on")
    assert ex.keyplanes.device_batches > 0   # device path actually ran
    assert ex.keyplanes.builds >= 1
    off = run("off")
    assert on == off


def test_warm_keyed_count_single_dispatch():
    """Acceptance: a warm keyed Count stays ONE device dispatch — the
    auto-mode threshold keeps single-key translation on the lock-free
    host snapshot, off the device."""
    rng = np.random.default_rng(3)
    h, ex, planner = _build_pair(rng, n_bits=60, n_cols=80)
    q = f'Count(Row(f="{_row_key(ROWS[0])}"))'
    ex.execute("kt", q, cache=False)
    ex.execute("kt", q, cache=False)          # warm compile + stacks
    d0 = planner.dispatches
    ex.execute("kt", q, cache=False)
    assert planner.dispatches - d0 == 1


# ---------------------------------------------------------------------------
# snapshot concurrency (the COW swap in core/translate.py)
# ---------------------------------------------------------------------------


def test_concurrent_allocate_while_lookup():
    """Readers run lock-free against published snapshots while a writer
    allocates batches: no torn state, version monotonic, fwd/rev stay a
    bijection, pre-existing keys never change ids."""
    store = TranslateStore()
    (seed_id,) = store.translate_keys(["seed"])
    stop = threading.Event()
    errors: list[str] = []

    def writer():
        try:
            for i in range(60):
                store.translate_keys([f"w{i}-{j}" for j in range(8)])
        except Exception as e:                       # pragma: no cover
            errors.append(f"writer: {e!r}")
        finally:
            stop.set()

    def reader():
        try:
            last_v = 0
            while not stop.is_set():
                if store.translate_key("seed", create=False) != seed_id:
                    errors.append("seed id changed")
                    return
                v, fwd, rev = store.snapshot()
                if v < last_v:
                    errors.append(f"version went backwards {last_v}->{v}")
                    return
                last_v = v
                if len(fwd) != len(rev):
                    errors.append("fwd/rev size mismatch")
                    return
                for k, id_ in list(fwd.items())[:5]:
                    if rev.get(id_) != k:
                        errors.append(f"rev[{id_}] != {k!r}")
                        return
                # Batched reverse over the snapshot's ids.
                ids = list(rev)[:8]
                names = store.translate_ids(ids)
                for id_, n in zip(ids, names):
                    if n is not None and fwd.get(n) != id_ and \
                            store.translate_key(n, create=False) != id_:
                        errors.append("reverse/forward disagree")
                        return
        except Exception as e:                       # pragma: no cover
            errors.append(f"reader: {e!r}")

    threads = [threading.Thread(target=writer)] + \
        [threading.Thread(target=reader) for _ in range(3)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    assert not errors, errors
    v, fwd, rev = store.snapshot()
    assert len(fwd) == 1 + 60 * 8
    assert len(set(fwd.values())) == len(fwd)        # ids all distinct
    assert sorted(fwd.values()) == sorted(rev)


def test_batch_allocation_one_version_bump():
    """translate_keys publishes ONE snapshot (one version bump, one
    index-epoch bump) per batch, not one per key."""
    h = Holder()
    idx = h.create_index("b", IndexOptions(keys=True))
    store = idx.translate_store
    v0 = store.version
    e0 = idx.epoch.value
    ids = store.translate_keys([f"k{i}" for i in range(100)])
    assert len(set(ids)) == 100
    assert store.version == v0 + 1
    assert idx.epoch.value == e0 + 1
    # All-hits batch: no bump at all.
    store.translate_keys([f"k{i}" for i in range(100)])
    assert store.version == v0 + 1
    assert idx.epoch.value == e0 + 1


# ---------------------------------------------------------------------------
# plane lifecycle (exec/keyplane.py)
# ---------------------------------------------------------------------------


def _keyed_idx():
    h = Holder()
    idx = h.create_index("p", IndexOptions(keys=True))
    return h, idx


def test_plane_rebuilds_on_version_bump(monkeypatch):
    """'on' mode: a store-version bump invalidates the plane; the next
    lookup rebuilds synchronously and resolves the new key."""
    monkeypatch.setenv("PILOSA_TPU_TRANSLATE_PLANES", "on")
    h, idx = _keyed_idx()
    store = idx.translate_store
    ida, idb = store.translate_keys(["a", "b"])
    cache = kp.KeyPlaneCache(planner=None)
    assert cache.lookup(idx, None, store, ["a", "b"]) == [ida, idb]
    assert cache.builds == 1
    # Same version: plane reused, no rebuild.
    assert cache.lookup(idx, None, store, ["b", "a"]) == [idb, ida]
    assert cache.builds == 1
    # Unknown key is a genuine miss, not an error.
    assert cache.lookup(idx, None, store, ["nope"]) == [None]
    # Allocation bumps the version -> synchronous rebuild on next use.
    (idc,) = store.translate_keys(["c"])
    assert cache.lookup(idx, None, store, ["a", "c"]) == [ida, idc]
    assert cache.builds == 2


def test_plane_auto_serves_stale_and_small_batches_host(monkeypatch):
    """'auto' mode: batches under MIN_DEVICE_BATCH skip the device; a
    stale plane serves what it has (correct-but-incomplete — new keys
    read as misses, never as wrong ids)."""
    h, idx = _keyed_idx()
    store = idx.translate_store
    keys = [f"k{i}" for i in range(kp.MIN_DEVICE_BATCH)]
    ids = store.translate_keys(keys)
    cache = kp.KeyPlaneCache(planner=None)
    monkeypatch.setenv("PILOSA_TPU_TRANSLATE_PLANES", "on")
    assert cache.lookup(idx, None, store, keys) == ids   # build plane
    monkeypatch.setenv("PILOSA_TPU_TRANSLATE_PLANES", "auto")
    # Small batch: host path (None = "device does not apply").
    assert cache.lookup(idx, None, store, keys[:4]) is None
    # Stale plane after a bump: resident keys resolve, the new key is a
    # miss for the host fallback to re-check.
    (idn,) = store.translate_keys(["new"])
    got = cache.lookup(idx, None, store, keys + ["new"])
    assert got[:-1] == ids and got[-1] is None
    assert cache.stale_served == 1
    monkeypatch.setenv("PILOSA_TPU_TRANSLATE_PLANES", "off")
    assert cache.lookup(idx, None, store, keys) is None


def test_plane_collision_bucket(monkeypatch):
    """Keys whose 64-bit fingerprints collide are excluded from the
    plane at build time and resolve from the host-side bucket."""
    table = {"x": 7, "y": 7, "a": 101, "b": 202, "nope": 303}

    def fake_hash(keys):
        return np.array([table[k] for k in keys], dtype=np.uint64)

    monkeypatch.setattr(kp, "hash_keys", fake_hash)
    monkeypatch.setenv("PILOSA_TPU_TRANSLATE_PLANES", "on")
    h, idx = _keyed_idx()
    store = idx.translate_store
    idx_ids = store.translate_keys(["x", "y", "a", "b"])
    mat, collisions, valid = kp.build_plane(store.snapshot()[1])
    assert set(collisions) == {"x", "y"}
    assert valid == 2
    cache = kp.KeyPlaneCache(planner=None)
    got = cache.lookup(idx, None, store, ["x", "y", "a", "b", "nope"])
    assert got == idx_ids + [None]
    assert cache.collision_hits == 2


def test_plane_kernels_roundtrip():
    """The residency KERNELS row for the keyplane class: count counts
    allocated slots, and_count counts probe membership, pair_count
    intersects two planes' hash sets."""
    fwd = {f"k{i}": i + 1 for i in range(10)}
    mat, _, valid = kp.build_plane(fwd)
    assert valid == 10
    assert int(kp.plane_count(mat)) == 10
    h = kp.hash_keys(["k3", "k7", "absent"])
    hi = (h >> np.uint64(32)).astype(np.uint32)
    lo = (h & np.uint64(0xFFFFFFFF)).astype(np.uint32)
    ids = np.asarray(kp.plane_lookup(mat, hi, lo))
    assert ids.tolist() == [4, 8, kp.MISS]
    assert int(kp.plane_and_count(mat, hi, lo)) == 2
    sub, _, _ = kp.build_plane({f"k{i}": i + 1 for i in range(5)})
    assert int(kp.plane_pair_count(sub, mat)) == 5


# ---------------------------------------------------------------------------
# replica-local read path (cluster/translate_sync.py)
# ---------------------------------------------------------------------------


class _CountingClient:
    """Transparent client proxy counting forward-translate RPCs."""

    def __init__(self, inner):
        self._inner = inner
        self.translate_calls = 0

    def translate_keys(self, *a, **kw):
        self.translate_calls += 1
        return self._inner.translate_keys(*a, **kw)

    def __getattr__(self, name):
        return getattr(self._inner, name)


def test_replica_synced_keys_zero_coordinator_calls():
    """Keys at or below the replication watermark resolve on the replica
    with ZERO coordinator RPCs; a batch with genuine misses costs
    exactly ONE batched RPC, not one per key."""
    from pilosa_tpu.cluster.harness import LocalCluster

    lc = LocalCluster(3)
    lc.create_index("k", IndexOptions(keys=True))
    lc.create_field("k", "f", FieldOptions(keys=True))
    synced = [f"s{i}" for i in range(10)]
    want = lc.nodes[0].translator("k", "f", synced)   # coordinator allocates
    lc.sync_translation()

    replica = lc.nodes[1].translator
    counting = _CountingClient(replica.client)
    replica.client = counting
    assert replica("k", "f", synced) == want
    assert replica("k", "f", list(reversed(synced))) == list(reversed(want))
    assert counting.translate_calls == 0
    # Mixed batch: the three misses travel in ONE RPC.
    got = replica("k", "f", synced[:2] + ["n1", "n2", "n3"])
    assert got[:2] == want[:2]
    assert len(set(got)) == 5
    assert counting.translate_calls == 1
    # The applied entries make the new keys replica-local too.
    assert replica("k", "f", ["n1", "n2", "n3"]) == got[2:]
    assert counting.translate_calls == 1


def test_entries_since_is_delta_not_full_scan():
    """Satellite (b): entries_since returns exactly the suffix after the
    cursor from the id-ordered log."""
    store = TranslateStore()
    store.translate_keys([f"k{i}" for i in range(20)])   # ids 1..20
    assert store.entries_since(20) == []
    tail = store.entries_since(17)
    assert tail == [(18, "k17"), (19, "k18"), (20, "k19")]
    assert [i for i, _ in store.entries_since(0)] == list(range(1, 21))
    # Out-of-order apply keeps the log id-sorted for later cursors.
    replica = TranslateStore()
    replica.apply_entries([(5, "k4"), (2, "k1")])
    assert replica.entries_since(0) == [(2, "k1"), (5, "k4")]
    assert replica.entries_since(2) == [(5, "k4")]
