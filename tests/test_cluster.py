"""Cluster-layer tests: placement math, in-process multi-node execution,
replication, node-failure failover, anti-entropy repair.

Models the reference's cluster_internal_test.go (pure placement math),
executor_test.go's MustRunCluster(t, 3) mirrors, and the clustertests
fault-injection suite (pumba pause → degraded reads → repair).
"""

import numpy as np
import pytest

from pilosa_tpu.cluster import Cluster, Node, fnv1a64, jump_hash, partition
from pilosa_tpu.cluster.cluster import ShardUnavailableError
from pilosa_tpu.cluster.harness import LocalCluster
from pilosa_tpu.cluster.sync import HolderSyncer, merge_block
from pilosa_tpu.config import SHARD_WIDTH
from pilosa_tpu.core import Holder, FieldOptions
from pilosa_tpu.exec import Executor


# -- placement math --------------------------------------------------------

def test_fnv1a64_vectors():
    # Published FNV-1a test vectors.
    assert fnv1a64(b"") == 0xCBF29CE484222325
    assert fnv1a64(b"a") == 0xAF63DC4C8601EC8C
    assert fnv1a64(b"foobar") == 0x85944171F73967E8


def test_jump_hash_properties():
    # Deterministic, in range, and monotone-stable as buckets grow.
    for key in (0, 1, 12345, 2**63):
        for n in (1, 2, 3, 8, 100):
            b = jump_hash(key, n)
            assert 0 <= b < n
    # Adding a bucket moves only a ~1/n fraction of keys.
    moved = sum(jump_hash(k, 8) != jump_hash(k, 9) for k in range(1000))
    assert moved < 1000 * 0.25


def test_partition_stability():
    p = partition("i", 0)
    assert partition("i", 0) == p
    assert 0 <= p < 256
    assert partition("other", 0) != p or partition("other", 1) != partition("i", 1)


def test_shard_nodes_replicas():
    nodes = [Node(id=f"n{i}") for i in range(4)]
    c = Cluster("n0", nodes, replica_n=2)
    owners = c.shard_nodes("i", 0)
    assert len(owners) == 2
    assert len({n.id for n in owners}) == 2
    # All nodes' views agree on placement.
    c2 = Cluster("n3", [Node(id=f"n{i}") for i in range(4)], replica_n=2)
    assert [n.id for n in c2.shard_nodes("i", 0)] == [n.id for n in owners]


def test_shards_by_node_unavailable():
    c = Cluster("n0", [Node(id="n0")], replica_n=1)
    with pytest.raises(ShardUnavailableError):
        c.shards_by_node([], "i", [0])


# -- multi-node execution --------------------------------------------------

def seed_cluster(lc: LocalCluster, n_shards=4, seed=5):
    lc.create_index("i")
    lc.create_field("i", "f")
    lc.create_field("i", "g")
    rng = np.random.default_rng(seed)
    total = n_shards * SHARD_WIDTH
    data = []
    for field in ("f", "g"):
        rows = rng.integers(0, 4, 2000)
        cols = rng.integers(0, total, 2000)
        data.append((rows, cols))
        # route writes per shard to owning nodes, like api.Import
        for shard in range(n_shards):
            m = (cols // SHARD_WIDTH) == shard
            if not m.any():
                continue
            node = lc[0].cluster.shard_nodes("i", shard)[0]
            peer = lc.client.peers[node.id]
            peer.holder.field("i", field).import_bits(rows[m], cols[m])
    return data


def expected_single_node(data, query):
    h = Holder()
    idx = h.create_index("i")
    for name, (rows, cols) in zip(("f", "g"), data):
        idx.create_field(name).import_bits(rows, cols)
    return Executor(h).execute("i", query)


CLUSTER_QUERIES = [
    "Count(Row(f=1))",
    "Count(Intersect(Row(f=1), Row(g=2)))",
    "Count(Union(Row(f=0), Row(g=3)))",
    "TopN(f, n=3)",
    "Rows(f)",
]


@pytest.mark.parametrize("query", CLUSTER_QUERIES)
def test_three_node_cluster_matches_single_node(query):
    lc = LocalCluster(3)
    data = seed_cluster(lc)
    want = expected_single_node(data, query)
    for node in range(3):
        got = lc.query("i", query, node=node)
        if hasattr(want[0], "columns"):
            assert np.array_equal(got[0].columns(), want[0].columns())
        else:
            assert got == want, (query, node)


def test_replicated_write_fanout():
    lc = LocalCluster(3, replica_n=2)
    lc.create_index("i")
    lc.create_field("i", "f")
    assert lc.query("i", "Set(5, f=1)") == [True]
    owners = [n.id for n in lc[0].cluster.shard_nodes("i", 0)]
    for cn in lc.nodes:
        frag = cn.holder.fragment("i", "f", "standard", 0)
        if cn.id in owners:
            assert frag is not None and frag.contains(1, 5), cn.id
        else:
            assert frag is None or not frag.contains(1, 5), cn.id


def test_attr_broadcast():
    lc = LocalCluster(3)
    lc.create_index("i")
    lc.create_field("i", "f")
    lc.query("i", 'SetRowAttrs(f, 1, color="red")')
    for cn in lc.nodes:
        assert cn.holder.field("i", "f").row_attr_store.attrs(1) == \
            {"color": "red"}


def test_failover_with_replicas():
    """Node goes down; reads fail over to replicas (executor.go:2492)."""
    lc = LocalCluster(3, replica_n=2)
    lc.create_index("i")
    lc.create_field("i", "f")
    # Write through the cluster so replicas hold copies.
    cols = [1, SHARD_WIDTH + 2, 2 * SHARD_WIDTH + 3]
    for c in cols:
        lc.query("i", f"Set({c}, f=7)")
    assert lc.query("i", "Count(Row(f=7))") == [3]
    # Fault injection: pause whichever non-coordinator node owns a shard.
    lc.down("node1")
    assert lc.query("i", "Count(Row(f=7))", node=0) == [3]
    assert lc[0].cluster.state == "DEGRADED"
    lc.up("node1")
    assert lc[0].cluster.state == "NORMAL"


def test_failover_without_replicas_fails():
    lc = LocalCluster(3, replica_n=1)
    lc.create_index("i")
    lc.create_field("i", "f")
    for s in range(3):
        lc.query("i", f"Set({s * SHARD_WIDTH}, f=1)")
    lc.down("node1")
    owned_by_down = [s for s in range(3)
                     if lc[0].cluster.shard_nodes("i", s)[0].id == "node1"]
    if owned_by_down:
        with pytest.raises(ShardUnavailableError):
            lc.query("i", "Count(Row(f=1))", node=0)


# -- anti-entropy ----------------------------------------------------------

def test_merge_block_majority():
    e = np.empty(0, np.uint64)
    local = (np.array([1, 2], np.uint64), np.array([10, 20], np.uint64))
    r1 = (np.array([1], np.uint64), np.array([10], np.uint64))
    r2 = (np.array([1, 3], np.uint64), np.array([10, 30], np.uint64))
    (lsets, lclears), remote = merge_block(local, [r1, r2])
    # bit (1,10): on all -> kept. (2,20): 1/3 -> cleared locally.
    # (3,30): 1/3 -> cleared on r2. majorityN = 2.
    assert lsets[0].tolist() == [] and lclears[0].tolist() == [2]
    (r1s, r1c), (r2s, r2c) = remote
    assert r1s[0].tolist() == [] and r1c[0].tolist() == []
    assert r2c[0].tolist() == [3]


def test_merge_block_even_split_keeps():
    local = (np.array([1], np.uint64), np.array([10], np.uint64))
    r1 = (np.empty(0, np.uint64), np.empty(0, np.uint64))
    (lsets, lclears), remote = merge_block(local, [r1])
    # 1 of 2 present, majorityN = (2+1)//2 = 1 -> kept; replica gets a set.
    assert lclears[0].tolist() == []
    assert remote[0][0][0].tolist() == [1]


def test_holder_syncer_repairs_replicas():
    lc = LocalCluster(3, replica_n=2)
    lc.create_index("i")
    lc.create_field("i", "f")
    lc.query("i", "Set(5, f=1) Set(6, f=1)")
    owners = lc[0].cluster.shard_nodes("i", 0)
    # Corrupt one replica: drop a bit directly.
    victim = lc.client.peers[owners[1].id]
    victim.holder.fragment("i", "f", "standard", 0).clear_bit(1, 6)
    primary = lc.client.peers[owners[0].id]
    syncer = HolderSyncer(primary.holder, primary.cluster, lc.client)
    repaired = syncer.sync_holder()
    assert repaired >= 1
    assert victim.holder.fragment("i", "f", "standard", 0).contains(1, 6)


def test_holder_syncer_repairs_attrs():
    """Attr stores converge through anti-entropy (VERDICT r2 missing #2;
    reference syncIndex/syncField holder.go:975-1067): row attrs,
    column attrs, and a divergent write that missed one node."""
    lc = LocalCluster(3, replica_n=2)
    lc.create_index("i")
    lc.create_field("i", "f")
    lc.query("i", "Set(5, f=1)")
    # Attrs land on nodes 0 and 1 but "miss" node 2 (simulated diverge).
    lc[0].holder.index("i").fields["f"].row_attr_store.set_attrs(
        1, {"name": "alpha"})
    lc[1].holder.index("i").fields["f"].row_attr_store.set_attrs(
        1, {"name": "alpha"})
    lc[0].holder.index("i").column_attr_store.set_attrs(5, {"city": "x"})
    node2 = lc[2]
    assert node2.holder.index("i").fields["f"].row_attr_store.attrs(1) == {}
    syncer = HolderSyncer(node2.holder, node2.cluster, lc.client)
    assert syncer.sync_holder() >= 1
    assert node2.holder.index("i").fields["f"].row_attr_store.attrs(1) == \
        {"name": "alpha"}
    assert node2.holder.index("i").column_attr_store.attrs(5) == {"city": "x"}


def test_node_event_pipeline():
    """NodeEvents flow from membership changes and the failure detector
    to subscribers (reference event.go:18-31 + ReceiveEvent)."""
    from pilosa_tpu.cluster.resize import check_nodes
    lc = LocalCluster(3, replica_n=2)
    c0 = lc[0].cluster
    events = []
    c0.subscribe(events.append)
    lc.client.down.add("node1")
    check_nodes(c0, lc.client)
    lc.client.down.discard("node1")
    check_nodes(c0, lc.client)
    assert [(e.type, e.node_id, e.state) for e in events] == [
        ("update", "node1", "DOWN"),
        ("update", "node1", "READY"),
    ]
    from pilosa_tpu.cluster.node import Node, URI
    c0.node_join(Node(id="nodeX", uri=URI(port=10999)))
    assert events[-1].type == "join" and events[-1].node_id == "nodeX"
    c0.node_leave("nodeX")
    assert events[-1].type == "leave"


def test_cross_node_invalidation_of_coordinator_cache():
    """Cluster-mode coordinator result caching (r3 weak #7): a write
    applied through node B must invalidate node A's cached read via the
    index-dirty broadcast, within the coalesce window."""
    import time
    from pilosa_tpu.cluster.harness import LocalCluster

    lc = LocalCluster(3, replica_n=1)
    lc.create_index("inv")
    lc.create_field("inv", "f")
    lc.query("inv", "Set(1, f=1)")

    # Coordinator A caches the read.
    assert lc.query("inv", "Count(Row(f=1))", node=0) == [1]
    assert lc.query("inv", "Count(Row(f=1))", node=0) == [1]  # cache hit

    # Find a column owned by a NON-coordinator node, write it via B.
    from pilosa_tpu.config import SHARD_WIDTH
    cl = lc[0].cluster
    col = next(s * SHARD_WIDTH + 7 for s in range(32)
               if cl.shard_nodes("inv", s)[0].id != "node0")
    lc.query("inv", f"Set({col}, f=1)", node=1)
    lc[1].dirty.flush_now()  # deterministic: skip the coalesce timer

    # A's cache entry is stale now; the next read recomputes.
    deadline = time.time() + 2.0
    while time.time() < deadline:
        if lc.query("inv", "Count(Row(f=1))", node=0) == [2]:
            break
        time.sleep(0.02)
    assert lc.query("inv", "Count(Row(f=1))", node=0) == [2]


def test_dirty_broadcast_coalesces():
    """A write burst sends at most ~2 broadcasts per window, not one
    per write."""
    from pilosa_tpu.cluster.harness import LocalCluster

    lc = LocalCluster(2)
    lc.create_index("burst")
    lc.create_field("burst", "f")
    sent = []
    orig = lc.client.send_message

    def counting(node, message):
        if message.get("type") == "index-dirty":
            sent.append(message)
        return orig(node, message)

    lc.client.send_message = counting
    for i in range(200):
        lc[0].executor.execute("burst", f"Set({i}, f=1)")
    lc[0].dirty.flush_now()
    # 200 writes in well under a window: first flush + trailing ones.
    assert len(sent) <= 8, len(sent)


def test_api_gated_by_cluster_state():
    """Reference api.go:99-125 validAPIMethods: queries, imports, and
    schema changes are refused while the cluster is RESIZING (a write
    accepted mid-resize could land on a ring position the committed
    topology and the holder GC won't honor) and while STARTING."""
    import pytest

    from pilosa_tpu.cluster import STATE_NORMAL, STATE_RESIZING, STATE_STARTING
    from pilosa_tpu.cluster.harness import LocalCluster
    from pilosa_tpu.errors import ApiMethodNotAllowedError
    from pilosa_tpu.server.api import API

    lc = LocalCluster(2)
    a = lc[0]
    api = API(a.holder, a.executor, cluster=a.cluster)
    api.create_index("gate")
    api.create_field("gate", "f")

    for state in (STATE_RESIZING, STATE_STARTING):
        a.cluster.set_state(state)
        for blocked in (
                lambda: api.query("gate", "Count(Row(f=1))"),
                lambda: api.create_index("gate2"),
                lambda: api.delete_index("gate"),
                lambda: api.create_field("gate", "g"),
                lambda: api.import_bits("gate", "f", [1], [2]),
                lambda: api.apply_schema([]),
        ):
            with pytest.raises(ApiMethodNotAllowedError):
                blocked()
        # Reads of cluster metadata stay up (operators must see status).
        assert api.status()["state"] == state
        api.schema()

    a.cluster.set_state(STATE_NORMAL)
    api.query("gate", "Count(Row(f=1))")  # flows again


def test_liveness_sweep_cannot_reopen_resizing_gate():
    """A check_nodes sweep landing mid-resize must not flip the state
    back to NORMAL (reopening the API gate while fragments move); the
    resize job restores the steady state itself on commit/abort."""
    from pilosa_tpu.cluster import STATE_RESIZING
    from pilosa_tpu.cluster.harness import LocalCluster
    from pilosa_tpu.cluster.resize import check_nodes

    lc = LocalCluster(3)
    a = lc[0]
    a.cluster.set_state(STATE_RESIZING)
    check_nodes(a.cluster, lc.client)
    assert a.cluster.state == STATE_RESIZING


def test_resize_state_broadcast_closes_peer_gates():
    """The RESIZING state reaches every node, not just the coordinator:
    a peer's API must refuse writes mid-resize too (a write accepted
    through a peer could land on a ring position the committed topology
    and holder GC won't honor), and the commit broadcast reopens it."""
    import pytest

    from pilosa_tpu.cluster import STATE_NORMAL, STATE_RESIZING
    from pilosa_tpu.cluster.harness import LocalCluster
    from pilosa_tpu.errors import ApiMethodNotAllowedError
    from pilosa_tpu.server.api import API

    lc = LocalCluster(3)
    coord, peer = lc[0], lc[1]
    peer_api = API(peer.holder, peer.executor, cluster=peer.cluster)
    peer_api.create_index("rs")

    # Coordinator announces the transition (ResizeJob._broadcast_state).
    msg = {"type": "cluster-state", "state": STATE_RESIZING}
    for n in coord.cluster.nodes:
        if n.id != coord.id:
            lc.client.send_message(n, msg)
    assert peer.cluster.state == STATE_RESIZING
    with pytest.raises(ApiMethodNotAllowedError):
        peer_api.import_bits("rs", "f", [1], [2])

    # Commit broadcast (cluster-status) ends the resize on the peer.
    status = {"type": "cluster-status",
              "nodes": [n.to_json() for n in coord.cluster.nodes],
              "version": coord.cluster.topology_version + 1}
    for n in coord.cluster.nodes:
        if n.id != coord.id:
            lc.client.send_message(n, status)
    assert peer.cluster.state == STATE_NORMAL
    peer_api.create_field("rs", "f")  # flows again


def test_apply_schema_fans_out_cluster_wide():
    """Reference API.ApplySchema (api.go:738): POST /schema on one node
    replicates the schema to every node; remote=true applies locally
    only (no re-fan-out)."""
    from pilosa_tpu.cluster.harness import LocalCluster
    from pilosa_tpu.server.api import API

    lc = LocalCluster(3)
    a = lc[0]
    api = API(a.holder, a.executor, cluster=a.cluster)
    schema = [{"name": "rep", "options": {},
               "fields": [{"name": "f", "options": {"type": "set"}}]}]
    api.apply_schema(schema)
    for i in range(3):
        idx = lc[i].holder.index("rep")
        assert idx is not None and idx.field("f") is not None, f"node {i}"

    # remote=true: local only.
    api2 = API(lc[1].holder, lc[1].executor, cluster=lc[1].cluster)
    api2.apply_schema([{"name": "solo", "options": {}, "fields": []}],
                      remote=True)
    assert lc[1].holder.index("solo") is not None
    assert lc[0].holder.index("solo") is None


def test_asymmetric_partition_does_not_mark_node_down():
    """SWIM-style indirect probes (VERDICT r4 #6): when THIS node cannot
    reach a peer but other members can, the peer is partitioned from
    us, not dead — the sweep must not emit node-down (which would
    trigger repair churn and DEGRADED)."""
    from pilosa_tpu.cluster import STATE_NORMAL
    from pilosa_tpu.cluster.resize import check_nodes

    lc = LocalCluster(3, replica_n=2)
    a = lc[0]

    class AsymClient:
        """node0 -> node2 link down; node1 -> node2 still up."""

        def __init__(self, inner, blocked_targets):
            self._inner = inner
            self._blocked = set(blocked_targets)

        def __getattr__(self, k):
            return getattr(self._inner, k)

        def probe(self, node):
            if node.id in self._blocked:
                raise ConnectionError("asymmetric link down")
            return self._inner.probe(node)

        def indirect_probe(self, via, target):
            # The intermediary's own link to the target (LocalClient
            # honors the true down set, not our blocked links).
            try:
                self._inner.probe(target)
                return True
            except ConnectionError:
                return False

    client = AsymClient(lc.client, {"node2"})
    events = []
    a.cluster.subscribe(lambda ev: events.append(ev))

    changed = check_nodes(a.cluster, client, discover=False)
    assert changed == []                       # no transition emitted
    assert a.cluster.node_by_id("node2").state != "DOWN"
    assert a.cluster.state == STATE_NORMAL     # no DEGRADED flap
    assert events == []                        # no repair trigger

    # Control: when the peer is REALLY dead, indirect probes fail too
    # and the sweep converges on DOWN as before.
    lc.client.down.add("node2")
    changed = check_nodes(a.cluster, client, discover=False)
    assert "node2" in changed
    assert a.cluster.node_by_id("node2").state == "DOWN"


# -- deadline propagation across the fan-out -------------------------------

def test_expired_deadline_cancels_fanout_no_partial_results():
    """A coordinator whose deadline already passed must cancel the whole
    query — zero remote legs dispatched, DeadlineExceededError raised —
    never return partial results."""
    from pilosa_tpu.qos import deadline as qdl

    lc = LocalCluster(3)
    seed_cluster(lc)
    remote_calls = []
    orig = lc.client.query_node

    def recording(node, index, query, shards, remote=True):
        remote_calls.append(node.id)
        return orig(node, index, query, shards, remote)

    lc.client.query_node = recording
    tok = qdl.set_current_deadline(qdl.Deadline(timeout=-1))
    try:
        with pytest.raises(qdl.DeadlineExceededError):
            lc.query("i", "Count(Row(f=1))", cache=False)
    finally:
        qdl.reset_current_deadline(tok)
        lc.client.query_node = orig
    assert remote_calls == []


def test_cancel_stops_failover_retry_wave():
    """A query cancelled while a node failure is being handled must NOT
    launch the failover retry wave: the coordinator raises instead of
    re-mapping the failed shards onto replicas and assembling a result
    the client already gave up on."""
    from pilosa_tpu.qos import deadline as qdl

    # 2 nodes, full replication: node1's shards can fail over to node0.
    # Seed BOTH replicas (seed_cluster writes primaries only, but this
    # control run needs the replica to hold real data).
    lc = LocalCluster(2, replica_n=2)
    lc.create_index("i")
    lc.create_field("i", "f")
    rng = np.random.default_rng(5)
    rows = rng.integers(0, 4, 2000)
    cols = rng.integers(0, 4 * SHARD_WIDTH, 2000)
    for cn in lc.nodes:
        cn.holder.field("i", "f").import_bits(rows, cols)
    want = expected_single_node([(rows, cols)], "Count(Row(f=1))")
    orig = lc.client.query_node

    # Control: a plain node failure DOES fail over and still produces
    # the complete result (this is the retry wave we then cancel).
    calls = []

    def failing_once(node, index, query, shards, remote=True):
        calls.append(node.id)
        if len(calls) == 1:
            raise ConnectionError(f"node {node.id} is down")
        return orig(node, index, query, shards, remote)

    lc.client.query_node = failing_once
    try:
        assert lc.query("i", "Count(Row(f=1))", cache=False) == want
    finally:
        lc.client.query_node = orig

    # Cancelled during the same failure: the between-wave deadline check
    # fires before any shard is re-mapped.
    dl = qdl.Deadline()  # no time limit; cancellation only

    def failing_cancelled(node, index, query, shards, remote=True):
        dl.cancel()
        raise ConnectionError(f"node {node.id} is down")

    lc.client.query_node = failing_cancelled
    tok = qdl.set_current_deadline(dl)
    try:
        with pytest.raises(qdl.DeadlineExceededError):
            lc.query("i", "Count(Row(f=1))", cache=False)
    finally:
        qdl.reset_current_deadline(tok)
        lc.client.query_node = orig


def test_deadline_rederived_on_remote_legs():
    """Each remote leg sees a peer-local token with the coordinator's
    absolute expiry (the X-Deadline re-derivation), not the coordinator's
    own token object."""
    from pilosa_tpu.qos import deadline as qdl

    lc = LocalCluster(3)
    data = seed_cluster(lc)
    seen = []
    for cn in lc.nodes[1:]:
        orig_handle = cn.handle_query

        def spying(index, query, shards, remote, _orig=orig_handle):
            seen.append(qdl.current_deadline())
            return _orig(index, query, shards, remote)

        cn.handle_query = spying

    coordinator_dl = qdl.Deadline(timeout=60)
    tok = qdl.set_current_deadline(coordinator_dl)
    try:
        got = lc.query("i", "Count(Row(f=1))", cache=False)
    finally:
        qdl.reset_current_deadline(tok)
    assert got == expected_single_node(data, "Count(Row(f=1))")
    assert seen, "no remote legs dispatched"
    for dl in seen:
        assert dl is not None and dl is not coordinator_dl
        assert dl.expires_at == pytest.approx(coordinator_dl.expires_at)


def test_write_fanout_down_replica_counted_and_marked_dirty():
    """A write whose DOWN replica was skipped is not silently forgotten:
    the skip is counted and the shard lands in the scrubber's dirty set
    (VERDICT: skipped-replica writes previously left no trace)."""
    from pilosa_tpu.obs.stats import MemoryStats

    lc = LocalCluster(2, replica_n=2)
    lc.create_index("i")
    lc.create_field("i", "f")
    stats = lc[0].cluster.stats = MemoryStats()
    lc.down("node1")
    lc.query("i", "Set(3, f=1)")
    assert stats.counter_value("cluster.replica_write_skipped") == 1
    assert ("i", 0) in lc[0].cluster.dirty_shards.drain()
    # No DOWN replica → no skip recorded.
    lc.up("node1")
    lc.query("i", "Set(4, f=1)")
    assert stats.counter_value("cluster.replica_write_skipped") == 1
    assert len(lc[0].cluster.dirty_shards) == 0


def test_scrubber_repairs_dirty_shard_after_replica_rejoin():
    """The dirty mark pays off: after the DOWN replica rejoins, one
    scrub pass pushes the missed write's consensus back into place."""
    from pilosa_tpu.cluster.scrub import Scrubber

    lc = LocalCluster(2, replica_n=2)
    lc.create_index("i")
    lc.create_field("i", "f")
    lc.query("i", "Set(1, f=1)")
    lc.down("node1")
    lc.query("i", "Set(2, f=1)")  # node1 misses this one
    lc.up("node1")
    assert len(lc[0].cluster.dirty_shards) == 1
    # node1's local copy is stale (scrub reads it directly, no failover).
    stale = lc[1].holder.fragment("i", "f", "standard", 0)
    assert stale.row(1).columns().tolist() == [1]

    scrub = Scrubber(lc[0].holder, lc[0].cluster, lc.client, None)

    class _Store:  # scrubber only touches quarantine + verify on this path
        class quarantine:
            @staticmethod
            def keys():
                return []

            @staticmethod
            def get(key):
                return None  # noqa: RET501 - explicit quarantine miss

        @staticmethod
        def _all_keys():
            return []

    scrub.store = _Store()
    out = scrub.scrub_pass()
    # >= 1: the index's existence field missed the write too.
    assert out["mismatch"] >= 1
    assert len(lc[0].cluster.dirty_shards) == 0
    assert stale.row(1).columns().tolist() == [1, 2]


def test_scrubber_skips_shard_this_node_no_longer_owns():
    """Resurrection guard: a dirty mark serviced AFTER a resize stripped
    local ownership must not push the stale former-owner copy back to
    the real owners — a bit the owners cleared would come back from the
    dead. The stale fragment is the holderCleaner's to delete."""
    from pilosa_tpu.cluster.scrub import Scrubber

    lc = LocalCluster(2, replica_n=1)
    lc.create_index("i")
    lc.create_field("i", "f")
    # Find a shard node0 does NOT own.
    shard = next(
        s for s in range(16)
        if all(n.id != "node0"
               for n in lc[0].cluster.shard_nodes("i", s)))
    col = shard * SHARD_WIDTH + 5
    lc.query("i", f"Set({col}, f=1)")  # lands on the real owner
    owner_frag = lc[1].holder.fragment("i", "f", "standard", shard)
    assert owner_frag.row(1).columns().tolist() == [col]

    # Simulate the race: node0 still holds a stale copy of the shard
    # (cleaner hasn't run) with a phantom bit the owner cleared, and a
    # stale dirty mark for it.
    v = lc[0].holder.index("i").field("f") \
        .create_view_if_not_exists("standard")
    stale = v.create_fragment_if_not_exists(shard)
    stale.bulk_import([1, 1], [col, col + 1])  # col+1 = phantom
    lc[0].cluster.dirty_shards.mark("i", shard)

    scrub = Scrubber(lc[0].holder, lc[0].cluster, lc.client, None)

    class _Store:
        class quarantine:
            @staticmethod
            def keys():
                return []

            @staticmethod
            def get(key):
                return None  # noqa: RET501 - explicit quarantine miss

        @staticmethod
        def _all_keys():
            return []

    scrub.store = _Store()
    out = scrub.scrub_pass()
    assert out["mismatch"] == 0
    # The phantom stayed quarantined to the stale local copy.
    assert owner_frag.row(1).columns().tolist() == [col]


def test_sync_merge_discards_plan_when_write_races():
    """Read-merge-write guard: a Clear that lands while a sync merge is
    in flight (after the local block read, before the plan applies) must
    not be undone by the stale plan — that would resurrect the cleared
    bit on every replica."""
    lc = LocalCluster(2, replica_n=2)
    lc.create_index("i")
    lc.create_field("i", "f")
    lc.query("i", "Set(5, f=1)")  # both owners hold bit 5
    # Diverge the copies directly so the syncer has a block to merge.
    lc[1].holder.fragment("i", "f", "standard", 0).bulk_import([1], [7])

    class _RacingClient:
        """Delegates to the real client, but the first block-data fetch
        happens concurrently with a Clear — the classic stale read."""

        def __init__(self, inner):
            self._inner = inner
            self._fired = False

        def __getattr__(self, name):
            return getattr(self._inner, name)

        def fragment_block_data(self, *a, **kw):
            if not self._fired:
                self._fired = True
                lc.query("i", "Clear(5, f=1)")  # races the merge
            return self._inner.fragment_block_data(*a, **kw)

    syncer = HolderSyncer(lc[0].holder, lc[0].cluster,
                          _RacingClient(lc.client))
    syncer.sync_holder()
    for node in (lc[0], lc[1]):
        frag = node.holder.fragment("i", "f", "standard", 0)
        assert 5 not in frag.row(1).columns().tolist(), node
