"""PQL parser tests — cases modeled on reference pql/pql_test.go behavior."""

import pytest

from pilosa_tpu import pql
from pilosa_tpu.pql import BETWEEN, EQ, GT, GTE, LT, LTE, NEQ, Call, Condition


def one(src: str) -> Call:
    q = pql.parse(src)
    assert len(q.calls) == 1, q.calls
    return q.calls[0]


def test_empty_query():
    assert pql.parse("").calls == []
    assert pql.parse("  \n\t ").calls == []


def test_simple_call():
    c = one("Row(f=10)")
    assert c.name == "Row"
    assert c.args == {"f": 10}
    assert c.children == []


def test_nested_calls():
    c = one("Count(Intersect(Row(a=1), Row(b=2)))")
    assert c.name == "Count"
    assert len(c.children) == 1
    inter = c.children[0]
    assert inter.name == "Intersect"
    assert [ch.name for ch in inter.children] == ["Row", "Row"]
    assert inter.children[0].args == {"a": 1}
    assert inter.children[1].args == {"b": 2}


def test_multiple_top_level_calls():
    q = pql.parse("Set(1, f=2) Count(Row(f=2))\nRow(f=3)")
    assert [c.name for c in q.calls] == ["Set", "Count", "Row"]
    assert q.write_call_n() == 1


def test_set_positional():
    c = one("Set(10, f=1)")
    assert c.args == {"_col": 10, "f": 1}


def test_set_with_timestamp():
    c = one("Set(10, f=1, 2001-02-03T04:05)")
    assert c.args == {"_col": 10, "f": 1, "_timestamp": "2001-02-03T04:05"}


def test_set_string_col():
    c = one('Set("abc", f=1)')
    assert c.args == {"_col": "abc", "f": 1}
    c = one("Set('x-y', f=1)")
    assert c.args == {"_col": "x-y", "f": 1}


def test_clear():
    c = one("Clear(7, f=3)")
    assert c.name == "Clear"
    assert c.args == {"_col": 7, "f": 3}


def test_clear_row():
    c = one("ClearRow(f=5)")
    assert c.args == {"f": 5}


def test_store():
    c = one("Store(Row(f=1), g=2)")
    assert c.name == "Store"
    assert len(c.children) == 1
    assert c.children[0].name == "Row"
    assert c.args == {"g": 2}


def test_set_row_attrs():
    c = one('SetRowAttrs(f, 10, color="blue", active=true, weight=1.5, x=null)')
    assert c.args == {
        "_field": "f", "_row": 10,
        "color": "blue", "active": True, "weight": 1.5, "x": None,
    }


def test_set_column_attrs():
    c = one('SetColumnAttrs(9, name="bob", qty=-3)')
    assert c.args == {"_col": 9, "name": "bob", "qty": -3}


def test_topn():
    c = one("TopN(f)")
    assert c.args == {"_field": "f"}
    c = one("TopN(f, n=25)")
    assert c.args == {"_field": "f", "n": 25}
    c = one("TopN(f, Row(g=1), n=10)")
    assert c.args == {"_field": "f", "n": 10}
    assert c.children[0].name == "Row"


def test_rows():
    c = one("Rows(f, previous=10, limit=100, column=3)")
    assert c.args == {"_field": "f", "previous": 10, "limit": 100, "column": 3}


def test_range_time_form():
    c = one("Range(f=1, from='1999-12-31T00:00', to='2002-01-01T02:00')")
    assert c.args == {"f": 1, "from": "1999-12-31T00:00", "to": "2002-01-01T02:00"}
    c = one("Range(f=1, 1999-12-31T00:00, 2002-01-01T02:00)")
    assert c.args == {"f": 1, "from": "1999-12-31T00:00", "to": "2002-01-01T02:00"}


def test_range_condition_form():
    c = one("Range(f > 5)")
    cond = c.args["f"]
    assert isinstance(cond, Condition)
    assert cond.op == GT and cond.value == 5


@pytest.mark.parametrize("op,tok", [
    ("==", EQ), ("!=", NEQ), ("<", LT), ("<=", LTE), (">", GT), (">=", GTE),
])
def test_conditions(op, tok):
    c = one(f"Row(f {op} 17)")
    cond = c.args["f"]
    assert isinstance(cond, Condition)
    assert cond.op == tok
    assert cond.value == 17


def test_between_condition():
    c = one("Row(f >< [4, 8])")
    cond = c.args["f"]
    assert cond.op == BETWEEN and cond.value == [4, 8]


def test_conditional_form():
    c = one("Row(4 < f <= 10)")
    cond = c.args["f"]
    assert cond.op == BETWEEN
    assert cond.value == [5, 10]
    c = one("Row(-2 <= f < 6)")
    assert c.args["f"].value == [-2, 5]


def test_negative_and_float_values():
    c = one("Row(a=-5, b=1.25, c=-0.5)")
    assert c.args == {"a": -5, "b": 1.25, "c": -0.5}


def test_list_values():
    c = one("Row(ids=[1, 2, 3])")
    assert c.args == {"ids": [1, 2, 3]}
    c = one('F(x=["a", "b"])')
    assert c.args == {"x": ["a", "b"]}


def test_bare_string_value():
    c = one("Options(Row(f=1), field=other-thing:x)")
    assert c.args["field"] == "other-thing:x"


def test_string_escapes():
    c = one(r'Row(f="a\"b")')
    assert c.args["f"] == 'a"b'


def test_call_as_arg_value():
    c = one("GroupBy(Rows(a), filter=Row(b=1))")
    assert c.children[0].name == "Rows"
    filt = c.args["filter"]
    assert isinstance(filt, Call) and filt.name == "Row"


def test_duplicate_arg_rejected():
    with pytest.raises(pql.ParseError):
        pql.parse("Row(f=1, f=2)")


def test_unterminated_call():
    with pytest.raises(pql.ParseError):
        pql.parse("Row(f=1")


def test_garbage_rejected():
    with pytest.raises(pql.ParseError):
        pql.parse("Row(f=1))")


def test_trailing_comma_generic():
    c = one("Union(Row(a=1), Row(b=2),)")
    assert len(c.children) == 2


def test_keyword_prefix_is_bare_string():
    c = one("Row(f=nullable)")
    assert c.args["f"] == "nullable"
    c = one("Row(f=truex)")
    assert c.args["f"] == "truex"


def test_call_str_roundtrip():
    c = one("Count(Intersect(Row(a=1), Row(b=2)))")
    assert str(c) == "Count(Intersect(Row(a=1), Row(b=2)))"
    c = one("Row(4 < f <= 10)")
    assert str(c) == "Row(f >< [5,10])"


def test_uint_arg_accessors():
    c = one("Row(f=10)")
    v, ok = c.uint_arg("f")
    assert (v, ok) == (10, True)
    v, ok = c.uint_arg("missing")
    assert (v, ok) == (0, False)
    assert c.field_arg() == "f"


def test_not_call():
    c = one("Not(Row(f=1))")
    assert c.name == "Not" and c.children[0].name == "Row"
