"""Persistent compile cache: a "second boot" must load compiled
programs from disk instead of recompiling.

The two-boot cycle is simulated in-process: ``jax.clear_caches()``
drops every in-memory jit executable (exactly what a restart loses)
while the on-disk cache survives, so re-running the same computation
must produce cache *hits* — the deterministic signal the cold-start CI
job and warmup report on.

The JAX cache knobs are process-global, so these tests share one cache
directory for the whole module and assert on counter deltas.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from pilosa_tpu.parallel import compile_cache


@pytest.fixture(scope="module")
def cache_dir(tmp_path_factory):
    import pathlib

    d = tmp_path_factory.mktemp("compile-cache")
    assert compile_cache.enable(str(d), stats=None)
    # The JAX cache dir is process-global and first-caller-wins; in a
    # full-suite run an earlier test's ServerNode may have enabled it
    # already — assert against whatever directory is actually live.
    return pathlib.Path(compile_cache.stats()["dir"])


def _cache_files(d):
    return [p for p in d.rglob("*") if p.is_file()]


def test_enable_reports_state(cache_dir):
    st = compile_cache.stats()
    assert st["enabled"]
    assert st["dir"] == str(cache_dir)


def _popcount_sum(x):
    bits = jnp.unpackbits(x.view(jnp.uint8), axis=-1)
    return bits.sum()


def test_second_boot_hits_disk_cache(cache_dir):
    # The cache key covers the lowered computation, which includes the
    # jit name — so "reboot" by re-jitting the SAME function, exactly
    # what a restarted planner does when it re-traces its kernels.
    x = jnp.asarray(np.arange(64, dtype=np.uint32))
    first = int(jax.jit(_popcount_sum)(x))
    assert _cache_files(cache_dir), "first boot must persist programs"
    before = compile_cache.stats()

    # "Restart": drop every in-memory executable, keep the disk cache.
    jax.clear_caches()

    second = int(jax.jit(_popcount_sum)(x))
    after = compile_cache.stats()
    assert second == first
    assert after["hits"] > before["hits"], (before, after)
    assert after["requests"] > before["requests"]


def test_stats_sink_fanout(cache_dir):
    class Sink:
        def __init__(self):
            self.counts = {}

        def count(self, name, n):
            self.counts[name] = self.counts.get(name, 0) + n

    sink = Sink()
    assert compile_cache.enable(str(cache_dir), stats=sink)
    try:
        def double(x):
            return x * 2

        y = jnp.asarray([1.0, 2.0])
        jax.jit(double)(y)
        jax.clear_caches()
        jax.jit(double)(y)
        assert sink.counts.get("compileCache.hits", 0) > 0
        assert sink.counts.get("compileCache.requests", 0) > 0
    finally:
        compile_cache.detach(sink)


def test_enable_without_dir_is_noop_query(cache_dir):
    # Passing an empty dir never flips state; it just answers whether
    # the cache is already on.
    assert compile_cache.enable("") is True


def test_planner_second_boot_reuses_programs(cache_dir):
    """End to end: a fresh MeshPlanner (new node, same machine) re-traces
    its kernels and the persistent cache serves them from disk."""
    from pilosa_tpu.config import SHARD_WIDTH
    from pilosa_tpu.core import Holder
    from pilosa_tpu.exec import Executor
    from pilosa_tpu.parallel import MeshPlanner, make_mesh

    mesh = make_mesh()

    def boot():
        h = Holder()
        idx = h.create_index("i")
        f = idx.create_field("f")
        f.import_bits([1] * 6, [s * SHARD_WIDTH + 3 for s in range(6)])
        ex = Executor(h, planner=MeshPlanner(h, mesh))
        return ex.execute("i", "Count(Row(f=1))")

    first = boot()
    before = compile_cache.stats()
    jax.clear_caches()
    second = boot()
    after = compile_cache.stats()
    assert second == first == [6]
    assert after["hits"] > before["hits"], (before, after)
