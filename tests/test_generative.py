"""Generative/property testing: random PQL call trees checked against a
pure-Python set model (the reference's internal/test/querygenerator.go
executor stress, rebuilt as model-based property tests)."""

import numpy as np
import pytest

from pilosa_tpu.config import SHARD_WIDTH
from pilosa_tpu.core import Holder
from pilosa_tpu.exec import Executor
from pilosa_tpu.parallel import MeshPlanner, make_mesh

FIELDS = ["a", "b", "c"]
ROWS = [1, 2, 3]


def _build(rng, n_bits=400, n_shards=3):
    h = Holder()
    idx = h.create_index("g")
    model: dict[tuple[str, int], set[int]] = {}
    existing: set[int] = set()
    cols_domain = n_shards * SHARD_WIDTH
    for fname in FIELDS:
        f = idx.create_field(fname)
        rows = rng.choice(ROWS, n_bits)
        cols = rng.integers(0, cols_domain, n_bits)
        f.import_bits(rows.astype(np.uint64), cols.astype(np.uint64))
        for r, c in zip(rows.tolist(), cols.tolist()):
            model.setdefault((fname, r), set()).add(c)
            existing.add(c)
    idx.add_existence(sorted(existing))
    return h, model, existing


def _gen_tree(rng, depth):
    """(pql_string_builder, model_evaluator) pair, recursively."""
    if depth == 0 or rng.random() < 0.35:
        f = FIELDS[rng.integers(0, len(FIELDS))]
        r = ROWS[rng.integers(0, len(ROWS))]
        return f"Row({f}={r})", ("row", f, r)
    op = ["Intersect", "Union", "Difference", "Xor", "Not", "Shift"][
        rng.integers(0, 6)]
    if op == "Not":
        q, t = _gen_tree(rng, depth - 1)
        return f"Not({q})", ("not", t)
    if op == "Shift":
        # Mix tiny shifts (intra-word), word-crossing ones, and the
        # occasional huge n (full-range device path; bits past a shard
        # edge fall off — per-shard semantics, test_planner:349).
        n = int([1, 7, 31, 32, 100, 4096, SHARD_WIDTH // 2][
            rng.integers(0, 7)])
        q, t = _gen_tree(rng, depth - 1)
        return f"Shift({q}, n={n})", ("shift", t, n)
    k = 2 + int(rng.integers(0, 2))
    subs = [_gen_tree(rng, depth - 1) for _ in range(k)]
    qs = ", ".join(s[0] for s in subs)
    return f"{op}({qs})", (op.lower(), [s[1] for s in subs])


def _eval_model(t, model, existing):
    kind = t[0]
    if kind == "row":
        return set(model.get((t[1], t[2]), set()))
    if kind == "not":
        return existing - _eval_model(t[1], model, existing)
    if kind == "shift":
        n = t[2]
        return {c + n for c in _eval_model(t[1], model, existing)
                if (c % SHARD_WIDTH) + n < SHARD_WIDTH}
    sets = [_eval_model(s, model, existing) for s in t[1]]
    acc = sets[0]
    for s in sets[1:]:
        if kind == "intersect":
            acc = acc & s
        elif kind == "union":
            acc = acc | s
        elif kind == "difference":
            acc = acc - s
        elif kind == "xor":
            acc = acc ^ s
    return acc


@pytest.mark.parametrize("seed", [11, 29, 47])
def test_random_trees_match_model(seed):
    """Count() of 40 random call trees agrees with the set model on BOTH
    executors (planner SPMD path and per-shard host path)."""
    rng = np.random.default_rng(seed)
    h, model, existing = _build(rng)
    fast = Executor(h, planner=MeshPlanner(h, make_mesh()))
    plain = Executor(h)
    for _ in range(40):
        q, tree = _gen_tree(rng, depth=3)
        want = len(_eval_model(tree, model, existing))
        got_fast = fast.execute("g", f"Count({q})", cache=False)
        got_plain = plain.execute("g", f"Count({q})")
        assert got_fast == [want] == got_plain, (q, want, got_fast,
                                                got_plain)


@pytest.mark.parametrize("seed", [13])
def test_random_trees_columns_match_model(seed):
    """Row results (actual columns) from random trees match the model."""
    rng = np.random.default_rng(seed)
    h, model, existing = _build(rng, n_bits=150, n_shards=2)
    ex = Executor(h, planner=MeshPlanner(h, make_mesh()))
    for _ in range(15):
        q, tree = _gen_tree(rng, depth=2)
        want = sorted(_eval_model(tree, model, existing))
        (row,) = ex.execute("g", q, cache=False)
        assert row.columns().tolist() == want, q


def test_random_writes_then_reads(rng):
    """Interleaved random Set/Clear keeps executor and model in sync
    (the mutation half of the generator stress)."""
    h = Holder()
    idx = h.create_index("g")
    idx.create_field("f")
    ex = Executor(h, planner=MeshPlanner(h, make_mesh()))
    model: set[int] = set()
    for i in range(120):
        col = int(rng.integers(0, 2 * SHARD_WIDTH))
        if rng.random() < 0.7:
            ex.execute("g", f"Set({col}, f=1)")
            model.add(col)
        else:
            ex.execute("g", f"Clear({col}, f=1)")
            model.discard(col)
        if i % 10 == 0:
            assert ex.execute("g", "Count(Row(f=1))") == [len(model)]
    assert ex.execute("g", "Count(Row(f=1))") == [len(model)]
