"""Plan-keyed result cache: unit, integration, and generative tests.

Covers the pilosa_tpu.cache subsystem end to end:

- Epoch per-shard semantics (selective invalidation, zero-arg compat);
- ResultCache byte-accounted LRU, TTL backstop, tenant partitions and
  fair-share eviction;
- canonical plan signatures (whitespace/format insensitivity);
- executor-level per-shard selectivity;
- the epoch-bump audit for the silent mutating paths (translate-key
  allocation, attr writes);
- cluster-mode remote-leg epoch-vector consistency, including the
  lost-broadcast recovery path;
- generative cache-on vs cache-off equivalence on a LocalCluster under
  random interleavings of mutations and queries.
"""

import numpy as np
import pytest

from pilosa_tpu.cache import ResultCache, estimate_result_size
from pilosa_tpu.cache.signature import plan_signature
from pilosa_tpu.config import SHARD_WIDTH
from pilosa_tpu.core.holder import Holder
from pilosa_tpu.core.index import Epoch
from pilosa_tpu.core.row import Row
from pilosa_tpu.exec.executor import Executor
from pilosa_tpu.exec.result import result_to_json
from pilosa_tpu.pql import parse


# -- Epoch: per-shard semantics ---------------------------------------------

def test_epoch_zero_arg_bump_floors_every_shard():
    e = Epoch()
    e.bump(shard=3)
    before = e.shard_epoch(7)
    e.bump()  # index-wide
    assert e.shard_epoch(3) > before
    assert e.shard_epoch(7) > before
    # every shard reads the same floor after a shardless bump
    assert e.shard_epoch(3) == e.shard_epoch(7) == e.value


def test_epoch_per_shard_bump_is_selective():
    e = Epoch()
    base0, base1 = e.shard_epoch(0), e.shard_epoch(1)
    e.bump(shard=0)
    assert e.shard_epoch(0) > base0
    assert e.shard_epoch(1) == base1
    assert e.max_shard_epoch([1]) == base1
    assert e.max_shard_epoch([0, 1]) == e.shard_epoch(0)


def test_epoch_value_stays_monotonic():
    e = Epoch()
    seen = [e.value]
    e.bump(shard=0)
    seen.append(e.value)
    e.bump_shards([1, 2])
    seen.append(e.value)
    e.bump()
    seen.append(e.value)
    assert seen == sorted(set(seen)), "every bump must advance .value"


def test_epoch_bump_shards_single_increment_per_batch():
    e = Epoch()
    v0 = e.value
    e.bump_shards([0, 1, 2, 3])
    assert e.value == v0 + 1  # one version for the whole batch
    assert all(e.shard_epoch(s) == v0 + 1 for s in range(4))


def test_epoch_listener_receives_shard():
    e = Epoch()
    calls = []
    e.subscribe(lambda shard=None: calls.append(shard))
    e.bump(shard=5)
    e.bump()
    e.bump_shards([1, 2])
    assert calls == [5, None, 1, 2]


def test_epoch_shard_vector():
    e = Epoch()
    e.bump(shard=0)
    e.bump(shard=2)
    vec = e.shard_vector([0, 1, 2])
    assert set(vec) == {0, 1, 2}
    assert vec[2] > vec[0] > vec[1]


# -- ResultCache: LRU bytes, TTL, tenants -----------------------------------

def _rows(n_cols):
    return [Row.from_columns(list(range(n_cols)))]


def test_cache_hit_requires_matching_stamp():
    c = ResultCache(max_bytes=1 << 20)
    c.put("t", ("k",), (1, 2, ()), [42])
    assert c.get("t", ("k",), (1, 2, ())) == [42]
    assert c.get("t", ("k",), (1, 3, ())) is None  # stale stamp
    # the stale entry was removed on sight, bytes reclaimed
    assert c.total_bytes == 0
    assert c.hits == 1 and c.misses == 1


def test_cache_lru_byte_accounting_and_eviction():
    one = estimate_result_size(_rows(64))
    c = ResultCache(max_bytes=3 * one)
    for i in range(5):
        c.put("t", (i,), (0,), _rows(64))
    assert c.total_bytes <= c.max_bytes
    assert c.evictions >= 2
    # oldest entries went first; newest survives
    assert c.get("t", (4,), (0,)) is not None
    assert c.get("t", (0,), (0,)) is None


def test_cache_reput_sole_entry_keeps_partition():
    """Regression: re-putting a key that is its partition's ONLY entry
    must not orphan the partition. Removing the old entry empties the
    partition (which deletes it); the insert must recreate it instead
    of raising KeyError on the byte account — two racing threads that
    both miss and both put hit exactly this path."""
    c = ResultCache(max_bytes=1 << 20)
    c.put("t", ("k",), (1,), [1])
    c.put("t", ("k",), (2,), [2])  # replace the sole entry
    assert c.get("t", ("k",), (2,)) == [2]
    snap = c.snapshot()
    assert snap["entries"] == 1
    assert snap["tenants"]["t"]["bytes"] == c.total_bytes > 0


def test_cache_get_refreshes_lru_position():
    one = estimate_result_size(_rows(64))
    c = ResultCache(max_bytes=2 * one + one // 2)
    c.put("t", ("a",), (0,), _rows(64))
    c.put("t", ("b",), (0,), _rows(64))
    assert c.get("t", ("a",), (0,)) is not None  # touch "a"
    c.put("t", ("c",), (0,), _rows(64))  # evicts the LRU: "b"
    assert c.get("t", ("a",), (0,)) is not None
    assert c.get("t", ("b",), (0,)) is None


def test_cache_oversized_entry_skipped():
    c = ResultCache(max_bytes=128)
    c.put("t", ("big",), (0,), _rows(10_000))
    assert c.total_bytes == 0
    assert c.get("t", ("big",), (0,)) is None


def test_cache_ttl_backstop():
    now = [0.0]
    c = ResultCache(max_bytes=1 << 20, ttl=10.0, clock=lambda: now[0])
    c.put("t", ("k",), (0,), [1])
    now[0] = 5.0
    assert c.get("t", ("k",), (0,)) == [1]
    now[0] = 11.0
    assert c.get("t", ("k",), (0,)) is None
    assert c.total_bytes == 0


def test_cache_tenant_isolation():
    c = ResultCache(max_bytes=1 << 20)
    c.put("a", ("k",), (0,), [1])
    assert c.get("b", ("k",), (0,)) is None
    assert c.get("a", ("k",), (0,)) == [1]


def test_cache_fair_share_eviction_protects_light_tenant():
    one = estimate_result_size(_rows(64))
    c = ResultCache(max_bytes=4 * one)
    c.put("light", ("x",), (0,), _rows(64))
    for i in range(10):  # heavy tenant churns its OWN partition
        c.put("heavy", (i,), (0,), _rows(64))
    assert c.get("light", ("x",), (0,)) is not None
    snap = c.snapshot()
    assert snap["tenants"]["light"]["entries"] == 1
    assert snap["evictions"] >= 6


def test_cache_snapshot_shape():
    c = ResultCache(max_bytes=1 << 20)
    c.put("", ("k",), (0,), [1])
    c.get("", ("k",), (0,))
    snap = c.snapshot()
    for key in ("bytes", "maxBytes", "entries", "hits", "misses",
                "evictions", "tenants"):
        assert key in snap
    assert snap["tenants"]["(default)"]["entries"] == 1


def test_cache_stats_counters():
    from pilosa_tpu.obs import MemoryStats
    stats = MemoryStats()
    c = ResultCache(max_bytes=1 << 20, stats=stats)
    c.put("t", ("k",), (0,), [1])
    c.get("t", ("k",), (0,))
    c.get("t", ("missing",), (0,))
    assert stats.counter_value("cache.hits") == 1
    assert stats.counter_value("cache.misses") == 1


# -- plan signatures ---------------------------------------------------------

def test_signature_normalizes_formatting():
    a = parse("Count(Row(f=1))")
    b = parse("Count( Row( f = 1 ) )")
    assert plan_signature(a) == plan_signature(b)


def test_signature_distinguishes_different_plans():
    assert (plan_signature(parse("Count(Row(f=1))"))
            != plan_signature(parse("Count(Row(f=2))")))
    assert (plan_signature(parse("Row(f=1)\nRow(f=2)"))
            != plan_signature(parse("Row(f=2)\nRow(f=1)")))


# -- executor: per-shard selectivity ----------------------------------------

def _seeded_executor(n_shards=2):
    h = Holder()
    idx = h.create_index("i")
    f = idx.create_field("f")
    rng = np.random.default_rng(7)
    cols = rng.integers(0, n_shards * SHARD_WIDTH, 1000)
    f.import_bits(np.ones(1000, dtype=np.int64), cols)
    return h, idx, Executor(h)


def test_executor_caches_and_invalidates():
    h, idx, ex = _seeded_executor()
    q = "Count(Row(f=1))"
    r1 = ex.execute("i", q)
    r2 = ex.execute("i", q)
    assert r1 == r2
    assert ex.result_cache.hits >= 1
    h.field("i", "f").set_bit(1, 5)
    r3 = ex.execute("i", q)
    assert r3 == [r1[0] + 1]


def test_executor_write_to_other_shard_keeps_entry():
    """The selective-invalidation payoff: a write to shard 1 must not
    evict a plan scoped to shard 0."""
    h, idx, ex = _seeded_executor()
    q = "Count(Row(f=1))"
    ex.execute("i", q, shards=[0])
    hits0 = ex.result_cache.hits
    h.field("i", "f").set_bit(1, SHARD_WIDTH + 5)  # shard 1 only
    ex.execute("i", q, shards=[0])
    assert ex.result_cache.hits == hits0 + 1, \
        "shard-0 plan must survive a shard-1 write"
    # and the same write DOES invalidate a plan that touches shard 1
    ex.execute("i", q, shards=[1])
    h.field("i", "f").set_bit(1, SHARD_WIDTH + 6)
    m0 = ex.result_cache.misses
    ex.execute("i", q, shards=[1])
    assert ex.result_cache.misses == m0 + 1


def test_executor_cache_disabled():
    h = Holder()
    h.create_index("i").create_field("f").set_bit(1, 1)
    ex = Executor(h, result_cache=False)
    assert ex.result_cache is None
    assert ex.execute("i", "Count(Row(f=1))") == [1]


def test_executor_cache_flag_bypasses():
    h, idx, ex = _seeded_executor()
    q = "Count(Row(f=1))"
    ex.execute("i", q)
    hits0 = ex.result_cache.hits
    ex.execute("i", q, cache=False)
    assert ex.result_cache.hits == hits0


# -- the epoch-bump audit: silent mutating paths ----------------------------

def test_translate_key_allocation_bumps_epoch():
    """New key allocation changes what Row(f="k") resolves to — it must
    be visible to cache stamps (the historical silent path)."""
    h = Holder()
    idx = h.create_index("i")
    before = idx.epoch.value
    idx.translate_store.translate_key("new-key")
    assert idx.epoch.value > before
    mid = idx.epoch.value
    idx.translate_store.translate_key("new-key")  # lookup, not allocation
    assert idx.epoch.value == mid


def test_translate_apply_entries_bumps_epoch():
    h = Holder()
    idx = h.create_index("i")
    before = idx.epoch.value
    idx.translate_store.apply_entries([(1, "a"), (2, "b")])
    assert idx.epoch.value > before
    mid = idx.epoch.value
    idx.translate_store.apply_entries([(1, "a")])  # no-op replay
    assert idx.epoch.value == mid


def test_attr_writes_bump_epoch():
    h = Holder()
    idx = h.create_index("i")
    f = idx.create_field("f")
    before = idx.epoch.value
    f.row_attr_store.set_attrs(1, {"color": "red"})
    assert idx.epoch.value > before
    mid = idx.epoch.value
    idx.column_attr_store.set_attrs(3, {"x": 1})
    assert idx.epoch.value > mid


def test_bulk_import_bumps_every_touched_shard():
    """Bulk imports merge fragments with bump_epoch=False and settle
    the epoch afterwards — every touched shard must land exactly one
    shard-scoped bump; untouched shards keep their cached plans."""
    h = Holder()
    idx = h.create_index("i")
    f = idx.create_field("f")
    cols = np.arange(0, 4 * SHARD_WIDTH, SHARD_WIDTH // 2)
    f.import_bits(np.ones(len(cols), dtype=np.int64), cols)
    before = {s: idx.epoch.shard_epoch(s) for s in range(5)}
    # steady state: import into shards 0-1 only
    cols2 = np.arange(0, 2 * SHARD_WIDTH, SHARD_WIDTH // 2)
    f.import_bits(2 * np.ones(len(cols2), dtype=np.int64), cols2)
    for s in (0, 1):
        assert idx.epoch.shard_epoch(s) > before[s], f"shard {s} silent"
    for s in (2, 3, 4):
        assert idx.epoch.shard_epoch(s) == before[s], \
            f"untouched shard {s} must keep its epoch"


def test_diskstore_attached_stores_keep_epoch(tmp_path):
    """DiskStore swaps in persistent attr/translate stores on open;
    the replacements must stay wired to the index epoch (the second
    silent path)."""
    from pilosa_tpu.storage.diskstore import DiskStore
    h = Holder()
    idx = h.create_index("i")
    idx.create_field("f")
    store = DiskStore(str(tmp_path), h)
    store.open()
    try:
        before = idx.epoch.value
        idx.translate_store.translate_key("k")
        assert idx.epoch.value > before
        mid = idx.epoch.value
        idx.column_attr_store.set_attrs(1, {"a": 1})
        assert idx.epoch.value > mid
        m2 = idx.epoch.value
        h.field("i", "f").row_attr_store.set_attrs(1, {"b": 2})
        assert idx.epoch.value > m2
    finally:
        store.close()


# -- cluster: remote-leg epoch vectors --------------------------------------

def _seed_local_cluster(n=3, n_shards=4, seed=5):
    from pilosa_tpu.cluster.harness import LocalCluster
    lc = LocalCluster(n)
    lc.create_index("i")
    lc.create_field("i", "f")
    rng = np.random.default_rng(seed)
    rows = rng.integers(0, 4, 2000)
    cols = rng.integers(0, n_shards * SHARD_WIDTH, 2000)
    for shard in range(n_shards):
        m = (cols // SHARD_WIDTH) == shard
        if not m.any():
            continue
        node = lc[0].cluster.shard_nodes("i", shard)[0]
        peer = lc.client.peers[node.id]
        peer.holder.field("i", "f").import_bits(rows[m], cols[m])
    for cn in lc.nodes:
        cn.dirty.flush_now()
    return lc


def _owned_column(lc, node_id, row=1):
    """A column in a shard whose primary is ``node_id``."""
    for shard in range(8):
        if lc[0].cluster.shard_nodes("i", shard)[0].id == node_id:
            return shard * SHARD_WIDTH + 11
    raise AssertionError(f"{node_id} owns no shard")


def test_cluster_coordinator_cache_hits_and_remote_invalidation():
    lc = _seed_local_cluster()
    q = "Count(Row(f=1))"
    r1 = lc.query("i", q)
    r2 = lc.query("i", q)
    assert r1 == r2
    ex = lc[0].executor
    assert ex.result_cache.hits >= 1
    # remote legs populated the coordinator's epoch table
    assert ex.remote_epochs.snapshot()["entries"] > 0
    # write on a REMOTE node; its dirty broadcast must invalidate the
    # coordinator's cached entry
    col = _owned_column(lc, "node1")
    lc.client.peers["node1"].holder.field("i", "f").set_bit(1, col)
    lc[1].dirty.flush_now()
    r3 = lc.query("i", q)
    assert r3 == [r1[0] + 1]
    assert lc.query("i", q) == r3  # and re-caches


def test_cluster_lost_broadcast_recovers_via_leg_vectors():
    """Drop every index-dirty broadcast: the coordinator serves stale
    (the documented window) until any uncached query re-runs the legs —
    their response vectors update the RemoteEpochTable, and the stale
    entry dies on the next lookup."""
    lc = _seed_local_cluster()
    orig = lc.client.send_message

    def drop_dirty(node, message):
        if message.get("type") == "index-dirty":
            return None
        return orig(node, message)

    lc.client.send_message = drop_dirty
    try:
        q = "Count(Row(f=1))"
        r1 = lc.query("i", q)
        col = _owned_column(lc, "node1")
        lc.client.peers["node1"].holder.field("i", "f").set_bit(1, col)
        lc[1].dirty.flush_now()  # broadcast dropped on the floor
        assert lc.query("i", q) == r1, "stale within the lost window"
        # an uncached pass re-runs the legs and observes fresh vectors
        fresh = lc.query("i", q, cache=False)
        assert fresh == [r1[0] + 1]
        assert lc.query("i", q) == fresh, \
            "leg-reported vectors must invalidate the stale entry"
    finally:
        lc.client.send_message = orig


def test_cluster_tenant_contextvar_partitions():
    from pilosa_tpu.cache.tenant import (
        reset_current_tenant,
        set_current_tenant,
    )
    lc = _seed_local_cluster()
    tok = set_current_tenant("alice")
    try:
        lc.query("i", "Count(Row(f=1))")
        lc.query("i", "Count(Row(f=1))")
    finally:
        reset_current_tenant(tok)
    snap = lc[0].executor.result_cache.snapshot()
    assert "alice" in snap["tenants"]


# -- generative equivalence: cache-on vs cache-off --------------------------

def _generative_run(ops, seed, n_nodes=2, n_shards=3):
    """Random interleaving of mutations and queries; every query's
    cache-served answer must be bit-identical to a cache-bypassing run
    at the same instant."""
    lc = _seed_local_cluster(n=n_nodes, n_shards=n_shards, seed=seed)
    rng = np.random.default_rng(seed)
    queries = [
        "Count(Row(f=1))",
        "Row(f=2)",
        "TopN(f, n=3)",
        "Count(Union(Row(f=0), Row(f=3)))",
        "Count(Intersect(Row(f=1), Row(f=2)))",
    ]
    checked = 0
    for _ in range(ops):
        op = rng.random()
        if op < 0.35:  # mutate through a random node's local holder
            node = lc.nodes[int(rng.integers(0, n_nodes))]
            row = int(rng.integers(0, 4))
            col = int(rng.integers(0, n_shards * SHARD_WIDTH))
            shard = col // SHARD_WIDTH
            owner = lc[0].cluster.shard_nodes("i", shard)[0].id
            f = lc.client.peers[owner].holder.field("i", "f")
            if rng.random() < 0.8:
                f.set_bit(row, col)
            else:
                f.clear_bit(row, col)
            if rng.random() < 0.7:  # most writes announce themselves
                lc.client.peers[owner].dirty.flush_now()
        else:
            # flush every pending mark first: equivalence is only
            # promised once broadcasts are delivered (the undelivered
            # window is bounded staleness by design, tested above)
            for cn in lc.nodes:
                cn.dirty.flush_now()
            q = queries[int(rng.integers(0, len(queries)))]
            node = int(rng.integers(0, n_nodes))
            got = lc.query("i", q, node=node)
            want = lc.query("i", q, node=node, cache=False)
            assert ([result_to_json(r) for r in got]
                    == [result_to_json(r) for r in want]), \
                f"divergence on {q!r} (seed={seed})"
            checked += 1
    assert checked > 0


def test_generative_equivalence_small():
    _generative_run(ops=40, seed=11)


@pytest.mark.slow
@pytest.mark.parametrize("seed", [1, 2, 3])
def test_generative_equivalence_long(seed):
    _generative_run(ops=150, seed=seed, n_nodes=3, n_shards=4)


# -- HTTP surface ------------------------------------------------------------

@pytest.mark.slow
def test_http_debug_cache_and_internal_exemption():
    import json
    import urllib.request

    from pilosa_tpu.server.node import ServerNode

    def req(base, method, path, body=None, headers=None):
        data = body.encode() if isinstance(body, str) else body
        r = urllib.request.Request(base + path, data=data, method=method)
        for k, v in (headers or {}).items():
            r.add_header(k, v)
        with urllib.request.urlopen(r, timeout=10) as resp:
            return resp.status, json.loads(resp.read() or b"{}")

    n = ServerNode(bind="127.0.0.1:0", use_planner=False)
    n.open()
    try:
        base = n.address
        req(base, "POST", "/index/i", "{}")
        req(base, "POST", "/index/i/field/f", "{}")
        req(base, "POST", "/index/i/query", "Set(1, f=1)")
        # repeated read populates + hits the cache
        req(base, "POST", "/index/i/query", "Count(Row(f=1))")
        req(base, "POST", "/index/i/query", "Count(Row(f=1))")
        _, snap = req(base, "GET", "/debug/cache")
        assert snap["enabled"] and snap["hits"] >= 1
        entries = snap["entries"]
        # INTERNAL-class requests must not populate tenant partitions
        for _ in range(2):
            req(base, "POST", "/index/i/query", "Count(Row(f=2))",
                headers={"X-Qos-Class": "internal"})
        _, snap2 = req(base, "GET", "/debug/cache")
        assert snap2["entries"] == entries
        # tenant partitions keyed by X-API-Key, reported on /debug/cache
        req(base, "POST", "/index/i/query", "Count(Row(f=1))",
            headers={"X-API-Key": "tenant-a"})
        _, snap3 = req(base, "GET", "/debug/cache")
        assert "tenant-a" in snap3["tenants"]
        # and occupancy rides /debug/overload next to quota state
        _, over = req(base, "GET", "/debug/overload")
        assert over["cache"]["bytes"] >= 0
        # /debug/vars carries the counters
        _, dv = req(base, "GET", "/debug/vars")
        assert any(k.startswith("cache.hits") for k in dv["counters"])
        # noCache bypasses: no new entries, no new hits
        h0 = snap3["hits"]
        req(base, "POST", "/index/i/query?noCache=true", "Count(Row(f=1))")
        _, snap4 = req(base, "GET", "/debug/cache")
        assert snap4["hits"] == h0
    finally:
        n.close()


@pytest.mark.slow
def test_http_result_cache_disabled_by_knob():
    from pilosa_tpu.server.node import ServerNode
    n = ServerNode(bind="127.0.0.1:0", use_planner=False,
                   result_cache_mb=0)
    n.open()
    try:
        import json
        import urllib.request
        with urllib.request.urlopen(n.address + "/debug/cache",
                                    timeout=10) as resp:
            snap = json.loads(resp.read())
        assert snap == {"enabled": False}
        assert n.executor.result_cache is None
    finally:
        n.close()
