// Native roaring codec: the hot host-side decode/encode loops.
//
// Mirrors pilosa_tpu/roaring.py (the Pilosa wire variant of
// roaring.go:1046 WriteTo / :5315 readers). This layer plays the role
// the reference's roaring/ package plays for its runtime: the
// performance-critical host path between wire/disk bytes and the dense
// uint32 blocks uploaded to the TPU.
//
// C ABI (ctypes-friendly), two-phase calls so Python owns allocation:
//   roaring_decode_count(buf, len)              -> bit count or -1
//   roaring_decode(buf, len, out_u64, cap)      -> n written or -1
//   roaring_encode_bound(pos_u64, n)            -> max encoded bytes
//   roaring_encode(pos_u64, n, out_u8, cap)     -> bytes written or -1
//   positions_to_words(pos_u64, n, words_u32, n_words)   (pos < n_words*32)
//   words_to_positions(words_u32, n_words, out_u64, cap) -> n
//   popcount_words(words_u32, n_words)          -> total set bits

#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <mutex>
#include <vector>

#if defined(__AVX2__)
#include <immintrin.h>
#endif
#if defined(__linux__)
#include <sys/mman.h>
#endif

namespace {

// --- write-combining radix partition -------------------------------------
//
// Shared by the bulk-import scatters: partitioning N random keys into
// ~1000 per-shard output streams is memory-bandwidth bound, and naive
// per-element stores both thrash the TLB (each store lands on a cold
// page of a 100s-of-MB buffer) and pollute the cache with lines that
// are written once and never read back.  Classic fix: stage 16 values
// (one cache line) per shard in an L1-resident buffer and flush full
// lines with non-temporal stores.  Segment starts are padded to
// 16-element alignment so every flush is a whole aligned line.

// Ask the kernel for 2 MiB pages on a large fresh buffer BEFORE first
// touch: on virtualized hosts each 4 KiB first-touch fault costs
// microseconds, so a 200 MB staging buffer pays >1 s in faults alone —
// with huge pages that drops to ~100 faults (and the TLB stops
// thrashing during the many-stream partition writes).
inline void advise_huge(void* p, size_t len) {
#if defined(__linux__) && defined(MADV_HUGEPAGE)
  uintptr_t a = (reinterpret_cast<uintptr_t>(p) + 4095) & ~uintptr_t(4095);
  uintptr_t e = (reinterpret_cast<uintptr_t>(p) + len) & ~uintptr_t(4095);
  if (e > a) madvise(reinterpret_cast<void*>(a), e - a, MADV_HUGEPAGE);
#else
  (void)p;
  (void)len;
#endif
}

void* pool_alloc_impl(int64_t bytes, int zero);
void pool_free_impl(void* p, int64_t bytes);

struct Partitioned {
  // start[s] (inclusive) .. end[s] (exclusive) index shard s's values
  // inside the 64-byte-aligned buffer `part` (a pool staging chunk,
  // returned to the pool on destruction).
  std::vector<int64_t> start, end;
  uint32_t* part = nullptr;
  void* owned = nullptr;
  int64_t owned_bytes = 0;
  ~Partitioned() {
    if (owned != nullptr) pool_free_impl(owned, owned_bytes);
  }
};

// --- recycled page pool ---------------------------------------------------
//
// Buffer pool for the large (100s of MB) block/staging buffers the bulk
// import path churns through. On virtualized hosts without working
// transparent huge pages (AnonHugePages: 0 even under MADV_HUGEPAGE),
// first-touch faults on a fresh anonymous mapping run at ~0.7-2 GB/s —
// slower than the import math itself — while an explicit memset of
// already-faulted memory runs at ~8 GB/s. Classic database answer:
// fault pages once (at boot via pool_reserve, or on first import) and
// recycle them forever. Plays the role the reference's mmapped
// fragment files + page cache play (fragment.go:311 openStorage):
// storage memory there is also faulted once and reused by the kernel.
//
// Best-fit freelist over privately mmapped chunks, 2 MiB granularity,
// split on allocation, never coalesced (the workload is a handful of
// large long-lived block arrays plus per-import staging; external
// fragmentation is bounded in practice and the limit evicts cleanly).
constexpr size_t kPoolAlign = size_t(2) << 20;  // 2 MiB granularity

struct PoolChunk {
  uint8_t* p;
  size_t sz;
};

std::mutex g_pool_mu;
std::vector<PoolChunk> g_pool_free;       // recycled, fault-warm chunks
size_t g_pool_free_bytes = 0;
size_t g_pool_limit = size_t(3) << 30;    // retained-bytes cap (3 GiB)
bool g_pool_limit_explicit = false;       // set via pool_set_limit: an
// operator-stated cap is a hard upper bound — pool_reserve must clamp
// to it, never raise it (ADVICE r4 #4).
int64_t g_pool_fresh_mmaps = 0;           // stats: cold allocations
int64_t g_pool_recycled = 0;              // stats: warm allocations

inline size_t pool_round(size_t bytes) {
  return (bytes + kPoolAlign - 1) & ~(kPoolAlign - 1);
}

// Recycling requires mmap (chunks are split at arbitrary offsets, so a
// freed pointer may be interior to its original mapping — munmap of a
// page range handles that; free() cannot). Off Linux the pool degrades
// to plain calloc/free with no freelist: correct, just not warm.
#if defined(__linux__)
uint8_t* pool_mmap(size_t sz) {
  void* p = mmap(nullptr, sz, PROT_READ | PROT_WRITE,
                 MAP_PRIVATE | MAP_ANONYMOUS, -1, 0);
  if (p == MAP_FAILED) return nullptr;
  advise_huge(p, sz);
  return static_cast<uint8_t*>(p);
}

void pool_munmap(uint8_t* p, size_t sz) { munmap(p, sz); }
#endif

// Evict largest-first while over the retained cap. Caller holds the lock.
#if !defined(__linux__)
void pool_enforce_limit_locked() {}  // freelist never populated off Linux
#else
void pool_enforce_limit_locked() {
  while (g_pool_free_bytes > g_pool_limit && !g_pool_free.empty()) {
    size_t worst = 0;
    for (size_t i = 1; i < g_pool_free.size(); i++)
      if (g_pool_free[i].sz > g_pool_free[worst].sz) worst = i;
    g_pool_free_bytes -= g_pool_free[worst].sz;
    pool_munmap(g_pool_free[worst].p, g_pool_free[worst].sz);
    g_pool_free[worst] = g_pool_free.back();
    g_pool_free.pop_back();
  }
}
#endif

// Allocate `bytes` (rounded to 2 MiB). zero!=0 gives np.zeros semantics;
// recycled chunks are memset (fast: pages already faulted), fresh mmaps
// are kernel-zeroed lazily. Returns nullptr on failure.
void* pool_alloc_impl(int64_t bytes, int zero) {
  if (bytes <= 0) return nullptr;
  size_t need = pool_round(static_cast<size_t>(bytes));
#if !defined(__linux__)
  return zero ? std::calloc(need, 1) : std::malloc(need);
#else
  uint8_t* p = nullptr;
  bool recycled = false;
  {
    std::lock_guard<std::mutex> g(g_pool_mu);
    size_t best = g_pool_free.size();
    for (size_t i = 0; i < g_pool_free.size(); i++)
      if (g_pool_free[i].sz >= need &&
          (best == g_pool_free.size() ||
           g_pool_free[i].sz < g_pool_free[best].sz))
        best = i;
    if (best < g_pool_free.size()) {
      PoolChunk c = g_pool_free[best];
      g_pool_free[best] = g_pool_free.back();
      g_pool_free.pop_back();
      g_pool_free_bytes -= c.sz;
      if (c.sz > need) {  // split: tail goes back on the freelist
        g_pool_free.push_back({c.p + need, c.sz - need});
        g_pool_free_bytes += c.sz - need;
      }
      p = c.p;
      recycled = true;
      g_pool_recycled++;
    }
  }
  if (p == nullptr) {
    p = pool_mmap(need);
    if (p == nullptr) return nullptr;
    std::lock_guard<std::mutex> g(g_pool_mu);
    g_pool_fresh_mmaps++;
  }
  if (zero && recycled) std::memset(p, 0, need);
  return p;
#endif
}

void pool_free_impl(void* p, int64_t bytes) {
  if (p == nullptr || bytes <= 0) return;
#if !defined(__linux__)
  std::free(p);
#else
  size_t sz = pool_round(static_cast<size_t>(bytes));
  std::lock_guard<std::mutex> g(g_pool_mu);
  g_pool_free.push_back({static_cast<uint8_t*>(p), sz});
  g_pool_free_bytes += sz;
  pool_enforce_limit_locked();
#endif
}

inline void flush_line(uint32_t* dst, const uint32_t* src) {
#if defined(__AVX2__)
  _mm256_stream_si256(reinterpret_cast<__m256i*>(dst),
                      _mm256_load_si256(reinterpret_cast<const __m256i*>(src)));
  _mm256_stream_si256(reinterpret_cast<__m256i*>(dst) + 1,
                      _mm256_load_si256(reinterpret_cast<const __m256i*>(src) + 1));
#else
  std::memcpy(dst, src, 64);
#endif
}

// Partition local positions (cols & mask) by shard (cols >> exp).
// Returns false on allocation failure.  Out-of-range shards are dropped,
// matching the historical scatter behaviour.
bool partition_by_shard(const uint64_t* cols, int64_t n, int exp,
                        int64_t n_shards, Partitioned& out) {
  const uint64_t mask = (1ULL << exp) - 1;
  std::vector<int64_t> count(n_shards, 0);
  for (int64_t k = 0; k < n; k++) {
    uint64_t s = cols[k] >> exp;
    if (static_cast<int64_t>(s) < n_shards) count[s]++;
  }
  out.start.resize(n_shards + 1);
  out.start[0] = 0;
  for (int64_t s = 0; s < n_shards; s++)
    out.start[s + 1] = out.start[s] + ((count[s] + 15) & ~15LL);
  const size_t part_bytes = ((out.start[n_shards] + 15) & ~15LL) * 4 + 64;
  out.owned = pool_alloc_impl(static_cast<int64_t>(part_bytes), 0);
  if (out.owned == nullptr) return false;
  out.owned_bytes = static_cast<int64_t>(part_bytes);
  out.part = reinterpret_cast<uint32_t*>(
      (reinterpret_cast<uintptr_t>(out.owned) + 63) & ~uintptr_t(63));
  std::vector<int64_t> head(out.start.begin(), out.start.end() - 1);
  std::vector<uint32_t> stage(n_shards * 16 + 16);
  uint32_t* stg = reinterpret_cast<uint32_t*>(
      (reinterpret_cast<uintptr_t>(stage.data()) + 63) & ~uintptr_t(63));
  std::vector<uint8_t> fill(n_shards, 0);
  for (int64_t k = 0; k < n; k++) {
    uint64_t c = cols[k];
    uint64_t s = c >> exp;
    if (static_cast<int64_t>(s) >= n_shards) continue;
    uint8_t f = fill[s];
    stg[s * 16 + f] = static_cast<uint32_t>(c & mask);
    if (++f == 16) {
      flush_line(&out.part[head[s]], &stg[s * 16]);
      head[s] += 16;
      f = 0;
    }
    fill[s] = f;
  }
#if defined(__AVX2__)
  _mm_sfence();
#endif
  for (int64_t s = 0; s < n_shards; s++)
    for (uint8_t i = 0; i < fill[s]; i++)
      out.part[head[s]++] = stg[s * 16 + i];
  out.end.assign(head.begin(), head.end());
  return true;
}

constexpr uint32_t kMagic = 12348;
// Official RoaringFormatSpec cookies (32-bit roaring; the constants are
// the public interchange format, reference roaring.go:5310-5313).
constexpr uint32_t kOfficialNoRuns = 12346;
constexpr uint32_t kOfficialRuns = 12347;
constexpr int kTypeArray = 1;
constexpr int kTypeBitmap = 2;
constexpr int kTypeRun = 3;
//: internal: official-spec run container — runs are (start, LENGTH)
//: pairs, unlike the pilosa variant's (start, last).
constexpr int kTypeRunOfficial = 4;
constexpr int kArrayMax = 4096;
constexpr int kRunMax = 2048;
constexpr int kBitmapWords64 = (1 << 16) / 64;

inline uint16_t rd16(const uint8_t* p) {
  return static_cast<uint16_t>(p[0] | (p[1] << 8));
}
inline uint32_t rd32(const uint8_t* p) {
  return static_cast<uint32_t>(p[0]) | (static_cast<uint32_t>(p[1]) << 8) |
         (static_cast<uint32_t>(p[2]) << 16) |
         (static_cast<uint32_t>(p[3]) << 24);
}
inline uint64_t rd64(const uint8_t* p) {
  uint64_t v = 0;
  std::memcpy(&v, p, 8);  // little-endian hosts only (x86/arm LE)
  return v;
}
inline void wr16(uint8_t* p, uint16_t v) {
  p[0] = v & 0xFF;
  p[1] = v >> 8;
}
inline void wr32(uint8_t* p, uint32_t v) {
  p[0] = v & 0xFF;
  p[1] = (v >> 8) & 0xFF;
  p[2] = (v >> 16) & 0xFF;
  p[3] = (v >> 24) & 0xFF;
}
inline void wr64(uint8_t* p, uint64_t v) { std::memcpy(p, &v, 8); }

struct Meta {
  uint64_t key;
  int typ;
  int n;
  uint32_t off;
};

// Official RoaringFormatSpec header+metas (readOfficialHeader behavior,
// roaring.go:5316-5374): u16 keys, cardinality-based container typing,
// run bitmap with cookie 12347, offset header present unless
// (runs && size < 4) — then containers are laid out sequentially.
int parse_official(const uint8_t* buf, int64_t len,
                   std::vector<Meta>* metas) {
  if (len < 8) return -1;
  uint32_t cookie = rd32(buf);
  uint32_t size;
  int64_t pos = 4;
  const uint8_t* run_bitmap = nullptr;
  bool have_runs = false;
  if (cookie == kOfficialNoRuns) {
    size = rd32(buf + 4);
    pos = 8;
  } else if ((cookie & 0xFFFF) == kOfficialRuns) {
    have_runs = true;
    size = (cookie >> 16) + 1;
    int64_t rb = (static_cast<int64_t>(size) + 7) / 8;
    if (pos + rb > len) return -1;
    run_bitmap = buf + pos;
    pos += rb;
  } else {
    return -1;
  }
  if (size > (1u << 16)) return -1;
  int64_t hdr = pos;
  if (pos + 4LL * size > len) return -1;
  pos += 4LL * size;
  bool have_offsets = !have_runs || size >= 4;
  const uint8_t* offsets = nullptr;
  if (have_offsets) {
    if (pos + 4LL * size > len) return -1;
    offsets = buf + pos;
    pos += 4LL * size;
    // Containers are sequential and non-overlapping; aliased or
    // decreasing offsets let a tiny buffer emit unbounded data.
    uint32_t prev = 0;
    for (uint32_t i = 0; i < size; i++) {
      uint32_t o = rd32(offsets + 4LL * i);
      if (o < pos || (i > 0 && o <= prev)) return -1;
      prev = o;
    }
  }
  int64_t data_off = pos;
  metas->resize(size);
  for (uint32_t i = 0; i < size; i++) {
    Meta& m = (*metas)[i];
    m.key = rd16(buf + hdr + 4LL * i);
    m.n = rd16(buf + hdr + 4LL * i + 2) + 1;
    bool is_run = run_bitmap && ((run_bitmap[i / 8] >> (i % 8)) & 1);
    // <=: official writers keep arrays up to EXACTLY 4096 values (the
    // reference's `card < ArrayMaxSize` typer misreads those; 4096 u16s
    // happen to be one bitmap's 8192 bytes, so nothing bounds-checks).
    m.typ = is_run ? kTypeRunOfficial
                   : (m.n <= kArrayMax ? kTypeArray : kTypeBitmap);
    if (offsets) {
      m.off = rd32(offsets + 4LL * i);
    } else {
      if (data_off > len || data_off > UINT32_MAX) return -1;
      m.off = static_cast<uint32_t>(data_off);
      switch (m.typ) {  // sequential layout: advance past this container
        case kTypeArray:
          data_off += 2LL * m.n;
          break;
        case kTypeBitmap:
          data_off += 8LL * kBitmapWords64;
          break;
        case kTypeRunOfficial: {
          if (data_off + 2 > len) return -1;
          int rc = rd16(buf + data_off);
          data_off += 2 + 4LL * rc;
          break;
        }
      }
    }
  }
  return static_cast<int>(size);
}

// Parse header + metas; returns container count or -1. Dispatches on
// the cookie: pilosa variant (12348) or official spec (12346/12347).
int parse_metas(const uint8_t* buf, int64_t len, std::vector<Meta>* metas) {
  if (len < 8) return -1;
  uint32_t cookie = rd32(buf);
  if ((cookie & 0xFFFF) != kMagic) return parse_official(buf, len, metas);
  int count = static_cast<int>(rd32(buf + 4));
  int64_t meta_off = 8;
  int64_t offs_off = meta_off + 12LL * count;
  if (count < 0 || offs_off + 4LL * count > len) return -1;
  metas->resize(count);
  for (int i = 0; i < count; i++) {
    const uint8_t* m = buf + meta_off + 12LL * i;
    (*metas)[i].key = rd64(m);
    (*metas)[i].typ = rd16(m + 8);
    (*metas)[i].n = rd16(m + 10) + 1;
    (*metas)[i].off = rd32(buf + offs_off + 4LL * i);
  }
  return count;
}

}  // namespace

extern "C" {

// --- pool C ABI (see "recycled page pool" above) --------------------------

void* pool_alloc(int64_t bytes, int zero) { return pool_alloc_impl(bytes, zero); }

void pool_free(void* p, int64_t bytes) { pool_free_impl(p, bytes); }

// Pre-fault `bytes` of pool memory (server boot / before a bulk load).
// Returns bytes actually reserved (0 on failure).
int64_t pool_reserve(int64_t bytes) {
#if !defined(__linux__)
  (void)bytes;
  return 0;  // no freelist off Linux — nothing to pre-fault
#else
  if (bytes <= 0) return 0;
  size_t sz = pool_round(static_cast<size_t>(bytes));
  {
    // Size the reserve under the lock BEFORE faulting pages: with an
    // operator-set cap (pool_set_limit) the cap is a hard bound — we
    // clamp the reserve to the remaining headroom instead of raising
    // the cap, and report the clamped size so the caller's top-up loop
    // sees the truth.
    std::lock_guard<std::mutex> g(g_pool_mu);
    if (g_pool_limit_explicit) {
      size_t headroom = g_pool_limit > g_pool_free_bytes
                            ? g_pool_limit - g_pool_free_bytes : 0;
      headroom &= ~(kPoolAlign - 1);
      if (headroom == 0) return 0;
      if (sz > headroom) sz = headroom;
    }
  }
  uint8_t* p = pool_mmap(sz);
  if (p == nullptr) return 0;
  std::memset(p, 0, sz);  // fault every page now, off the import path
  std::lock_guard<std::mutex> g(g_pool_mu);
  if (!g_pool_limit_explicit) {
    // Without an operator cap, a reserve states intent and may grow
    // the default cap to cover itself — but only now that the chunk
    // exists (growing before a failed mmap would permanently inflate
    // the cap with nothing to show for it).
    if (g_pool_limit < g_pool_free_bytes + sz)
      g_pool_limit = g_pool_free_bytes + sz;
  } else if (g_pool_free_bytes + sz > g_pool_limit) {
    // Headroom moved between the clamp and here (a concurrent
    // pool_free refilled the pool): re-clamp by trimming the tail of
    // the chunk we just faulted, so the return value never overstates
    // what the pool retained.
    size_t keep = g_pool_limit > g_pool_free_bytes
                      ? (g_pool_limit - g_pool_free_bytes)
                            & ~(kPoolAlign - 1)
                      : 0;
    if (keep == 0) {
      pool_munmap(p, sz);
      return 0;
    }
    pool_munmap(p + keep, sz - keep);
    sz = keep;
  }
  g_pool_free.push_back({p, sz});
  g_pool_free_bytes += sz;
  g_pool_fresh_mmaps++;
  pool_enforce_limit_locked();
  return static_cast<int64_t>(sz);
#endif
}

void pool_set_limit(int64_t bytes) {
  std::lock_guard<std::mutex> g(g_pool_mu);
  g_pool_limit = bytes < 0 ? 0 : static_cast<size_t>(bytes);
  g_pool_limit_explicit = true;
  pool_enforce_limit_locked();
}

// out[0]=free_bytes out[1]=fresh_mmaps out[2]=recycled_allocs out[3]=limit
void pool_stats(int64_t* out) {
  std::lock_guard<std::mutex> g(g_pool_mu);
  out[0] = static_cast<int64_t>(g_pool_free_bytes);
  out[1] = g_pool_fresh_mmaps;
  out[2] = g_pool_recycled;
  out[3] = static_cast<int64_t>(g_pool_limit);
}

int64_t roaring_decode_count(const uint8_t* buf, int64_t len) {
  std::vector<Meta> metas;
  if (parse_metas(buf, len, &metas) < 0) return -1;
  int64_t total = 0;
  for (const Meta& m : metas) total += m.n;
  // Allocation-DoS guard: a 4-byte run can legitimately encode 65536
  // values, so len*16384 bounds any honest buffer; claims beyond it are
  // adversarial (the caller allocates `total` uint64s).
  if (total > len * 16384 + 65536) return -1;
  return total;
}

int64_t roaring_decode(const uint8_t* buf, int64_t len, uint64_t* out,
                       int64_t cap) {
  std::vector<Meta> metas;
  if (parse_metas(buf, len, &metas) < 0) return -1;
  int64_t n_out = 0;
  for (const Meta& m : metas) {
    uint64_t base = m.key << 16;
    const uint8_t* data = buf + m.off;
    // cap guards below use the ACTUAL content (popcounts, run
    // lengths), never the claimed N: an adversarial buffer can claim
    // N=1 while a run/bitmap emits 65536 values — trusting N was a
    // heap overflow (caller allocates from roaring_decode_count).
    switch (m.typ) {
      case kTypeArray: {
        if (m.off + 2LL * m.n > len) return -1;
        if (n_out + m.n > cap) return -1;
        for (int i = 0; i < m.n; i++) out[n_out++] = base + rd16(data + 2 * i);
        break;
      }
      case kTypeBitmap: {
        if (m.off + 8LL * kBitmapWords64 > len) return -1;
        for (int w = 0; w < kBitmapWords64; w++) {
          uint64_t word = rd64(data + 8 * w);
          if (word && n_out + __builtin_popcountll(word) > cap) return -1;
          while (word) {
            int b = __builtin_ctzll(word);
            out[n_out++] = base + (static_cast<uint64_t>(w) << 6) + b;
            word &= word - 1;
          }
        }
        break;
      }
      case kTypeRun:
      case kTypeRunOfficial: {
        if (m.off + 2 > len) return -1;
        int run_n = rd16(data);
        if (m.off + 2 + 4LL * run_n > len) return -1;
        for (int r = 0; r < run_n; r++) {
          uint16_t start = rd16(data + 2 + 4 * r);
          uint32_t last = rd16(data + 2 + 4 * r + 2);
          if (m.typ == kTypeRunOfficial) {
            // Official spec stores (start, length): last = start + len
            // (officialRoaringIterator conversion, roaring.go:1404).
            last += start;
            if (last > 0xFFFF) return -1;
          }
          if (last >= start &&
              n_out + (static_cast<int64_t>(last) - start + 1) > cap)
            return -1;
          for (uint32_t v = start; v <= last; v++) out[n_out++] = base + v;
        }
        break;
      }
      default:
        return -1;
    }
  }
  return n_out;
}

int64_t roaring_encode_bound(const uint64_t* pos, int64_t n) {
  (void)pos;
  // Worst case: every position its own array container.
  return 8 + n * (12 + 4 + 2) + 16;
}

int64_t roaring_encode(const uint64_t* pos, int64_t n, uint8_t* out,
                       int64_t cap) {
  // PRECONDITION: pos is strictly increasing (unique-sorted); the Python
  // binding (pilosa_tpu/native/__init__.py encode_roaring) enforces it.
  // Group sorted positions by 2^16 key; pick run/array/bitmap per the
  // reference's optimize() economics (roaring.go:2334).
  struct Cont {
    uint64_t key;
    int typ;
    int n;
    int64_t start;  // index into pos
  };
  std::vector<Cont> conts;
  int64_t i = 0;
  while (i < n) {
    uint64_t key = pos[i] >> 16;
    int64_t j = i;
    int runs = 1;
    while (j + 1 < n && (pos[j + 1] >> 16) == key) {
      if (pos[j + 1] != pos[j] + 1) runs++;
      j++;
    }
    int cn = static_cast<int>(j - i + 1);
    int run_size = 2 + 4 * runs;
    int array_size = 2 * cn;
    int typ;
    if (runs <= kRunMax && run_size < array_size && run_size < 8192)
      typ = kTypeRun;
    else if (cn <= kArrayMax)
      typ = kTypeArray;
    else
      typ = kTypeBitmap;
    conts.push_back({key, typ, cn, i});
    i = j + 1;
  }
  int count = static_cast<int>(conts.size());
  int64_t head = 8 + 12LL * count + 4LL * count;
  if (head > cap) return -1;
  wr32(out, kMagic);
  wr32(out + 4, static_cast<uint32_t>(count));
  int64_t off = head;
  for (int c = 0; c < count; c++) {
    const Cont& ct = conts[c];
    uint8_t* m = out + 8 + 12LL * c;
    wr64(m, ct.key);
    wr16(m + 8, static_cast<uint16_t>(ct.typ));
    wr16(m + 10, static_cast<uint16_t>(ct.n - 1));
    wr32(out + 8 + 12LL * count + 4LL * c, static_cast<uint32_t>(off));
    // payload
    const uint64_t* p = pos + ct.start;
    if (ct.typ == kTypeArray) {
      if (off + 2LL * ct.n > cap) return -1;
      for (int k = 0; k < ct.n; k++)
        wr16(out + off + 2LL * k, static_cast<uint16_t>(p[k] & 0xFFFF));
      off += 2LL * ct.n;
    } else if (ct.typ == kTypeRun) {
      // recount runs
      std::vector<std::pair<uint16_t, uint16_t>> runs;
      uint16_t start = static_cast<uint16_t>(p[0] & 0xFFFF);
      uint16_t prev = start;
      for (int k = 1; k < ct.n; k++) {
        uint16_t v = static_cast<uint16_t>(p[k] & 0xFFFF);
        if (v != prev + 1) {
          runs.emplace_back(start, prev);
          start = v;
        }
        prev = v;
      }
      runs.emplace_back(start, prev);
      int64_t sz = 2 + 4LL * runs.size();
      if (off + sz > cap) return -1;
      wr16(out + off, static_cast<uint16_t>(runs.size()));
      for (size_t r = 0; r < runs.size(); r++) {
        wr16(out + off + 2 + 4 * r, runs[r].first);
        wr16(out + off + 2 + 4 * r + 2, runs[r].second);
      }
      off += sz;
    } else {
      int64_t sz = 8LL * kBitmapWords64;
      if (off + sz > cap) return -1;
      std::memset(out + off, 0, sz);
      for (int k = 0; k < ct.n; k++) {
        uint16_t v = static_cast<uint16_t>(p[k] & 0xFFFF);
        out[off + (v >> 3)] |= static_cast<uint8_t>(1u << (v & 7));
      }
      off += sz;
    }
  }
  return off;
}

void positions_to_words(const uint64_t* pos, int64_t n, uint32_t* words,
                        int64_t n_words) {
  for (int64_t k = 0; k < n; k++) {
    uint64_t p = pos[k];
    int64_t w = static_cast<int64_t>(p >> 5);
    if (w < n_words) words[w] |= 1u << (p & 31);
  }
}

int64_t words_to_positions(const uint32_t* words, int64_t n_words,
                           uint64_t* out, int64_t cap) {
  int64_t n = 0;
  for (int64_t w = 0; w < n_words; w++) {
    uint32_t word = words[w];
    while (word) {
      int b = __builtin_ctz(word);
      if (n >= cap) return -1;
      out[n++] = (static_cast<uint64_t>(w) << 5) + b;
      word &= word - 1;
    }
  }
  return n;
}

int64_t popcount_words(const uint32_t* words, int64_t n_words) {
  int64_t total = 0;
  for (int64_t w = 0; w < n_words; w++)
    total += __builtin_popcount(words[w]);
  return total;
}

int64_t intersection_count_words(const uint32_t* a, const uint32_t* b,
                                 int64_t n_words) {
  // Fused popcount(a & b): the CPU-baseline analog of the reference's
  // intersectionCountBitmapBitmap (roaring.go:3121) — POPCNT over the
  // word stream, autovectorized at -O3 -march=native. ctypes releases
  // the GIL around this call, so per-shard threads scale like the
  // reference's goroutine worker pool.
  int64_t total = 0;
  for (int64_t w = 0; w < n_words; w++)
    total += __builtin_popcount(a[w] & b[w]);
  return total;
}

void scatter_row_blocks(const uint64_t* cols, int64_t n, int exp,
                        uint32_t* blocks, int64_t n_shards,
                        int64_t words_per_shard, uint8_t* touched,
                        int64_t* block_counts) {
  // Bulk-import scatter for ONE bitmap row: absolute column ids ->
  // dense per-shard word blocks (blocks is [n_shards, words_per_shard],
  // caller-zeroed). The order-insensitivity of a bitset means no sort
  // is needed — this is what lets the import path hit memory-bandwidth
  // rates where the reference walks roaring containers per bit batch
  // (fragment.go:1997 -> AddN).
  //
  // Two-phase for cache locality: a direct scatter across all blocks
  // misses cache on every bit (the block array spans 100s of MB), so
  // first radix-PARTITION the local positions by shard — the ~n_shards
  // sequential write heads stay cache-resident — then set bits shard by
  // shard into one block that fits in L2.
  const uint64_t mask = (1ULL << exp) - 1;
  // Small batches: partitioning overhead isn't worth it.
  Partitioned p;
  if (n < (1 << 18) || n_shards <= 4 ||
      !partition_by_shard(cols, n, exp, n_shards, p)) {
    for (int64_t k = 0; k < n; k++) {
      uint64_t c = cols[k];
      uint64_t shard = c >> exp;
      if (static_cast<int64_t>(shard) >= n_shards) continue;
      uint64_t local = c & mask;
      blocks[shard * words_per_shard + (local >> 5)] |= 1u << (local & 31);
      touched[shard] = 1;
    }
    if (block_counts != nullptr)
      for (int64_t s = 0; s < n_shards; s++) {
        if (!touched[s]) continue;
        const uint32_t* block = blocks + s * words_per_shard;
        int64_t total = 0;
        for (int64_t w = 0; w < words_per_shard; w++)
          total += __builtin_popcount(block[w]);
        block_counts[s] = total;
      }
    return;
  }
  for (int64_t s = 0; s < n_shards; s++) {
    int64_t lo = p.start[s], hi = p.end[s];
    if (lo == hi) continue;
    uint32_t* block = blocks + s * words_per_shard;
    // Count fresh bits inline (the old word is already loaded for the
    // OR) — cheaper than a whole-block popcount pass afterwards, which
    // would re-read every word including the untouched majority.
    int64_t cnt = 0;
    for (int64_t k = lo; k < hi; k++) {
      uint32_t local = p.part[k];
      uint32_t bit = 1u << (local & 31);
      uint32_t old = block[local >> 5];
      cnt += (old & bit) == 0;
      block[local >> 5] = old | bit;
    }
    touched[s] = 1;
    if (block_counts != nullptr) block_counts[s] = cnt;
  }
}

int scatter_bsi_blocks(const uint64_t* cols, const int64_t* vals, int64_t n,
                       int exp, int depth, uint32_t* blocks,
                       int64_t n_shards, int64_t words_per_shard,
                       uint8_t* touched, int64_t* block_counts) {
  // BSI bulk-import scatter: (column, value) pairs -> dense bit-plane
  // blocks. blocks is [n_shards, depth+2, words_per_shard] caller-zeroed;
  // per shard the row order is exists, sign, then magnitude planes
  // (fragment BSI layout, reference fragment.go:87-93 + importValue
  // :2205). Shard-partitions first so one shard's whole plane set
  // (~(depth+2) * 128 KiB) stays cache-resident while its bits land.
  // Duplicated columns follow last-write-wins like sequential writes:
  // the exists plane doubles as the batch's seen-set (caller guarantees
  // a FRESH view), so a duplicate clears the column across all planes
  // before the new value lands — no host-side dedupe sort needed.
  const uint64_t mask = (1ULL << exp) - 1;
  const int64_t rows = depth + 2;
  std::vector<int64_t> count(n_shards, 0);
  for (int64_t k = 0; k < n; k++) {
    uint64_t shard = cols[k] >> exp;
    if (static_cast<int64_t>(shard) < n_shards) count[shard]++;
  }
  // Same write-combining partition as scatter_row_blocks, with a
  // parallel int64 value stream (16 values = two 64-byte lines).
  std::vector<int64_t> start(n_shards + 1);
  start[0] = 0;
  for (int64_t s = 0; s < n_shards; s++)
    start[s + 1] = start[s] + ((count[s] + 15) & ~15LL);
  const int64_t cap = start[n_shards];
  const size_t plocal_bytes = ((cap + 15) & ~15LL) * 4 + 64;
  const size_t pval_bytes = ((cap + 15) & ~15LL) * 8 + 128;
  void* plocal_owned = pool_alloc_impl(static_cast<int64_t>(plocal_bytes), 0);
  void* pval_owned = pool_alloc_impl(static_cast<int64_t>(pval_bytes), 0);
  uint32_t* plocal = reinterpret_cast<uint32_t*>(
      (reinterpret_cast<uintptr_t>(plocal_owned) + 63) & ~uintptr_t(63));
  int64_t* pval = reinterpret_cast<int64_t*>(
      (reinterpret_cast<uintptr_t>(pval_owned) + 63) & ~uintptr_t(63));
  struct StagingGuard {
    void *a, *b;
    int64_t an, bn;
    ~StagingGuard() {
      if (a != nullptr) pool_free_impl(a, an);
      if (b != nullptr) pool_free_impl(b, bn);
    }
  } guard{plocal_owned, pval_owned, static_cast<int64_t>(plocal_bytes),
          static_cast<int64_t>(pval_bytes)};
  std::vector<int64_t> head(start.begin(), start.end() - 1);
  std::vector<uint32_t> lstage_v(n_shards * 16 + 16);
  std::vector<int64_t> vstage_v(n_shards * 16 + 8);
  uint32_t* lstage = reinterpret_cast<uint32_t*>(
      (reinterpret_cast<uintptr_t>(lstage_v.data()) + 63) & ~uintptr_t(63));
  int64_t* vstage = reinterpret_cast<int64_t*>(
      (reinterpret_cast<uintptr_t>(vstage_v.data()) + 63) & ~uintptr_t(63));
  std::vector<uint8_t> fill(n_shards, 0);
  if (plocal_owned == nullptr || pval_owned == nullptr) {
    return -1;  // alloc failure: caller must fall back (blocks untouched)
  }
  for (int64_t k = 0; k < n; k++) {
    uint64_t c = cols[k];
    uint64_t shard = c >> exp;
    if (static_cast<int64_t>(shard) >= n_shards) continue;
    uint8_t f = fill[shard];
    lstage[shard * 16 + f] = static_cast<uint32_t>(c & mask);
    vstage[shard * 16 + f] = vals[k];
    if (++f == 16) {
      flush_line(&plocal[head[shard]], &lstage[shard * 16]);
#if defined(__AVX2__)
      for (int i = 0; i < 4; i++)
        _mm256_stream_si256(
            reinterpret_cast<__m256i*>(&pval[head[shard]]) + i,
            _mm256_load_si256(
                reinterpret_cast<const __m256i*>(&vstage[shard * 16]) + i));
#else
      std::memcpy(&pval[head[shard]], &vstage[shard * 16], 128);
#endif
      head[shard] += 16;
      f = 0;
    }
    fill[shard] = f;
  }
#if defined(__AVX2__)
  _mm_sfence();
#endif
  for (int64_t s = 0; s < n_shards; s++)
    for (uint8_t i = 0; i < fill[s]; i++) {
      plocal[head[s]] = lstage[s * 16 + i];
      pval[head[s]++] = vstage[s * 16 + i];
    }
  // Value-at-a-time per shard with INLINE per-plane counts: dedupe
  // first against the exists plane (walking the shard's slice BACKWARD
  // keeps the LAST occurrence, preserving last-write-wins on the
  // caller-guaranteed fresh view), so the set passes never need the
  // all-plane duplicate clear, and counts come for free with the sets —
  // a whole-plane popcount pass afterwards would re-read
  // (depth+2)*128 KiB per shard, dwarfing a sparse batch.
  std::vector<int64_t> cnt(rows);
  for (int64_t s = 0; s < n_shards; s++) {
    int64_t lo = start[s], hi = head[s];
    if (lo == hi) continue;
    uint32_t* base = blocks + s * rows * words_per_shard;
    std::fill(cnt.begin(), cnt.end(), 0);
    for (int64_t k = hi - 1; k >= lo; k--) {
      // Each value touches ~popcount(v) plane words that all share ONE
      // word offset w but sit 128 KiB apart — every touch is a cache
      // miss. The addresses are computable from (plocal, pval) alone,
      // so prefetch a few values ahead: exists + sign + the magnitude's
      // set-bit planes.
      if (k - 4 >= lo) {
        uint32_t pl = plocal[k - 4];
        int64_t pw = pl >> 5;
        __builtin_prefetch(&base[pw], 1);
        int64_t pv = pval[k - 4];
        uint64_t pm;
        if (pv < 0) {
          __builtin_prefetch(&base[words_per_shard + pw], 1);
          pm = static_cast<uint64_t>(-pv);
        } else {
          pm = static_cast<uint64_t>(pv);
        }
        while (pm) {
          int i = __builtin_ctzll(pm);
          pm &= pm - 1;
          if (i < depth)
            __builtin_prefetch(&base[(2 + i) * words_per_shard + pw], 1);
        }
      }
      uint32_t local = plocal[k];
      int64_t w = local >> 5;
      uint32_t bit = 1u << (local & 31);
      if (base[w] & bit) continue;  // a later write owns this column
      base[w] |= bit;  // exists row
      cnt[0]++;
      int64_t v = pval[k];
      uint64_t mag;
      if (v < 0) {
        base[words_per_shard + w] |= bit;  // sign row
        cnt[1]++;
        mag = static_cast<uint64_t>(-v);
      } else {
        mag = static_cast<uint64_t>(v);
      }
      while (mag) {
        int i = __builtin_ctzll(mag);
        mag &= mag - 1;
        if (i < depth) {
          base[(2 + i) * words_per_shard + w] |= bit;
          cnt[2 + i]++;
        }
      }
    }
    touched[s] = 1;
    if (block_counts != nullptr)
      for (int64_t r = 0; r < rows; r++) block_counts[s * rows + r] = cnt[r];
  }
  return 0;
}

}  // extern "C"
