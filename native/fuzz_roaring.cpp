// Fuzz harness for the roaring wire codec (pilosa variant + official
// RoaringFormatSpec). Built with ASan/UBSan (`make -C native fuzz`) and
// run in CI via tests/test_roaring_fuzz.py; the full 1e5-iteration run
// is `./fuzz_roaring 100000`.
//
// Strategy (the reference's go-fuzz harness for UnmarshalBinary,
// roaring/fuzzer.go, rebuilt as a self-contained deterministic loop):
//   1. build VALID buffers of all three container types in both formats
//      from a seeded RNG,
//   2. mutate them (byte flips, truncations, splices, length-field
//      tweaks), and
//   3. feed them to roaring_decode_count/roaring_decode, asserting only
//      memory-safety invariants (no OOB — sanitizers — and the output
//      never exceeds the promised capacity).

#include <cstdint>
#include <cstdio>
#include <algorithm>
#include <cstdlib>
#include <cstring>
#include <vector>

extern "C" {
int64_t roaring_decode_count(const uint8_t* buf, int64_t len);
int64_t roaring_decode(const uint8_t* buf, int64_t len, uint64_t* out,
                       int64_t cap);
int64_t roaring_encode_bound(const uint64_t* pos, int64_t n);
int64_t roaring_encode(const uint64_t* pos, int64_t n, uint8_t* out,
                       int64_t cap);
void scatter_row_blocks(const uint64_t* cols, int64_t n, int exp,
                        uint32_t* blocks, int64_t n_shards,
                        int64_t words_per_shard, uint8_t* touched,
                        int64_t* block_counts);
int scatter_bsi_blocks(const uint64_t* cols, const int64_t* vals,
                       int64_t n, int exp, int depth, uint32_t* blocks,
                       int64_t n_shards, int64_t words_per_shard,
                       uint8_t* touched, int64_t* block_counts);
}

namespace {

uint64_t rng_state = 0x9E3779B97F4A7C15ull;
uint64_t rnd() {  // xorshift64*
  uint64_t x = rng_state;
  x ^= x >> 12;
  x ^= x << 25;
  x ^= x >> 27;
  rng_state = x;
  return x * 0x2545F4914F6CDD1Dull;
}

void wr16v(std::vector<uint8_t>* b, uint16_t v) {
  b->push_back(v & 0xFF);
  b->push_back(v >> 8);
}
void wr32v(std::vector<uint8_t>* b, uint32_t v) {
  wr16v(b, v & 0xFFFF);
  wr16v(b, v >> 16);
}

// A valid pilosa-variant buffer via the real encoder.
std::vector<uint8_t> seed_pilosa() {
  int n = 1 + rnd() % 2048;
  std::vector<uint64_t> pos(n);
  uint64_t cur = rnd() % 512;
  for (int i = 0; i < n; i++) {
    cur += 1 + rnd() % ((rnd() % 7 == 0) ? 70000 : 3);
    pos[i] = cur;
  }
  int64_t cap = roaring_encode_bound(pos.data(), n);
  std::vector<uint8_t> out(cap);
  int64_t sz = roaring_encode(pos.data(), n, out.data(), cap);
  if (sz < 0) abort();  // encoder must handle its own output
  out.resize(sz);
  return out;
}

// A valid official-spec buffer, hand-assembled (array/bitmap/run mix).
std::vector<uint8_t> seed_official() {
  int n_cont = 1 + rnd() % 5;
  bool with_runs = rnd() & 1;
  std::vector<uint8_t> run_flags((n_cont + 7) / 8, 0);
  struct C {
    uint16_t key;
    int type;  // 0 array, 1 bitmap, 2 run
    std::vector<uint8_t> payload;
    int card;
  };
  std::vector<C> cs(n_cont);
  for (int i = 0; i < n_cont; i++) {
    cs[i].key = i * (1 + rnd() % 3);
    int t = with_runs ? rnd() % 3 : rnd() % 2;
    cs[i].type = t;
    if (t == 0) {  // array
      int card = 1 + rnd() % 1024;
      cs[i].card = card;
      uint16_t v = rnd() % 64;
      for (int k = 0; k < card; k++) {
        wr16v(&cs[i].payload, v);
        v += 1 + rnd() % 8;
        if (v < 8) break;  // wrapped; card shrinks below — fix card
      }
      cs[i].card = cs[i].payload.size() / 2;
    } else if (t == 1) {  // bitmap
      cs[i].payload.resize(8192);
      int card = 0;
      for (int w = 0; w < 8192; w++) {
        uint8_t byte = (w % 3 == 0) ? (rnd() & 0xFF) : 0;
        cs[i].payload[w] = byte;
        card += __builtin_popcount(byte);
      }
      if (card == 0) {
        cs[i].payload[0] = 1;
        card = 1;
      }
      cs[i].card = card;
    } else {  // run: (start, length) pairs
      run_flags[i / 8] |= 1 << (i % 8);
      int rn = 1 + rnd() % 16;
      wr16v(&cs[i].payload, rn);
      uint32_t v = rnd() % 64;
      int card = 0;
      for (int r = 0; r < rn; r++) {
        uint32_t length = rnd() % 32;
        if (v + length > 0xFFFF) {
          v = 0;
          length = 1;
        }
        wr16v(&cs[i].payload, v);
        wr16v(&cs[i].payload, length);
        card += length + 1;
        v += length + 2 + rnd() % 16;
      }
      cs[i].card = card;
    }
  }
  std::vector<uint8_t> buf;
  bool have_offsets;
  if (with_runs) {
    wr32v(&buf, 12347u | ((n_cont - 1) << 16));
    buf.insert(buf.end(), run_flags.begin(), run_flags.end());
    have_offsets = n_cont >= 4;
  } else {
    wr32v(&buf, 12346u);
    wr32v(&buf, n_cont);
    have_offsets = true;
  }
  for (auto& c : cs) {
    wr16v(&buf, c.key);
    wr16v(&buf, c.card - 1);
  }
  size_t off_at = buf.size();
  if (have_offsets) buf.resize(buf.size() + 4 * n_cont);
  for (int i = 0; i < n_cont; i++) {
    if (have_offsets) {
      uint32_t o = buf.size();
      memcpy(&buf[off_at + 4 * i], &o, 4);
    }
    buf.insert(buf.end(), cs[i].payload.begin(), cs[i].payload.end());
  }
  return buf;
}

void mutate(std::vector<uint8_t>* buf) {
  if (buf->empty()) return;
  switch (rnd() % 5) {
    case 0: {  // flip random bytes
      int k = 1 + rnd() % 8;
      for (int i = 0; i < k; i++)
        (*buf)[rnd() % buf->size()] ^= 1 << (rnd() % 8);
      break;
    }
    case 1:  // truncate
      buf->resize(rnd() % buf->size());
      break;
    case 2: {  // splice random garbage
      size_t at = rnd() % buf->size();
      int k = 1 + rnd() % 16;
      for (int i = 0; i < k && at + i < buf->size(); i++)
        (*buf)[at + i] = rnd() & 0xFF;
      break;
    }
    case 3: {  // tweak a 16-bit length-ish field
      if (buf->size() >= 10) {
        size_t at = 4 + rnd() % (buf->size() - 6);
        uint16_t v = rnd() % 5 == 0 ? 0xFFFF : (rnd() & 0xFF);
        memcpy(&(*buf)[at], &v, 2);
      }
      break;
    }
    case 4:  // extend with garbage
      for (int i = 0; i < 32; i++) buf->push_back(rnd() & 0xFF);
      break;
  }
}

void one_case(const std::vector<uint8_t>& buf, bool valid) {
  int64_t n = roaring_decode_count(buf.data(), buf.size());
  if (n < 0) {
    if (valid) {
      fprintf(stderr, "decode_count rejected a VALID buffer\n");
      abort();
    }
    return;
  }
  if (n > (1 << 26)) return;  // absurd-but-bounded claim: skip alloc
  std::vector<uint64_t> out(n ? n : 1);
  int64_t got = roaring_decode(buf.data(), buf.size(), out.data(), n);
  if (got > n) {
    fprintf(stderr, "decode overran promised capacity: %lld > %lld\n",
            (long long)got, (long long)n);
    abort();
  }
  if (valid && got != n) {
    fprintf(stderr, "decode of a VALID buffer returned %lld, claimed %lld\n",
            (long long)got, (long long)n);
    abort();
  }
}

// Sanitizer exercise of the bulk-import scatters (ASan/UBSan build):
// random shapes through both entry points, including the staged
// write-combining partition and the inline-count paths.
void scatter_case() {
  int exp = 14 + rnd() % 3;                       // small shard widths
  int64_t wps = (1LL << exp) / 32;
  int64_t n_shards = 1 + rnd() % 40;
  int64_t n = 1 + rnd() % 300000;                 // crosses the 2^18 gate
  std::vector<uint64_t> cols(n);
  uint64_t span = (n_shards + 1) << exp;          // some out-of-range
  for (auto& c : cols) c = rnd() % span;
  std::vector<uint32_t> blocks(n_shards * wps, 0);
  std::vector<uint8_t> touched(n_shards, 0);
  std::vector<int64_t> counts(n_shards, 0);
  scatter_row_blocks(cols.data(), n, exp, blocks.data(), n_shards, wps,
                     touched.data(), counts.data());
  int depth = 1 + rnd() % 20;
  std::vector<int64_t> vals(n);
  for (auto& v : vals)
    v = (int64_t)(rnd() % (1ULL << depth)) - (1LL << (depth - 1));
  std::vector<uint32_t> bblocks(n_shards * (depth + 2) * wps, 0);
  std::fill(touched.begin(), touched.end(), 0);
  std::vector<int64_t> bcounts(n_shards * (depth + 2), 0);
  scatter_bsi_blocks(cols.data(), vals.data(), n, exp, depth,
                     bblocks.data(), n_shards, wps, touched.data(),
                     bcounts.data());
}

}  // namespace

int main(int argc, char** argv) {
  long iters = argc > 1 ? atol(argv[1]) : 100000;
  if (argc > 2) rng_state ^= atol(argv[2]);
  for (long i = 0; i < iters; i++) {
    std::vector<uint8_t> buf = (rnd() & 1) ? seed_pilosa() : seed_official();
    bool valid = i % 3 == 0;  // 1/3 stay valid (decode must ACCEPT them)
    if (!valid) {
      int k = 1 + rnd() % 4;
      for (int m = 0; m < k; m++) mutate(&buf);
    }
    one_case(buf, valid);
    if (i % 2000 == 0) scatter_case();
  }
  printf("fuzz_roaring: %ld iterations clean\n", iters);
  return 0;
}
